"""graftmesh: sharding- and collective-aware program auditing.

graftlint (tier 1) proves what SYNTAX can prove; graftaudit (tier 2)
walks the traced single-device PROGRAM. Neither sees the property the
whole system is named for: FetchSGD's round is supposed to cost ONE
compressed all-reduce on the wire, and the ROADMAP's top two open
items (million-client sharded client state, multi-controller pod
scale-out) are sharding refactors that tier 1/2 would wave through
even when they break that contract. This module is the THIRD tier: it
traces the three round programs and the scanned span under EXPLICIT
multi-device meshes — the real constructors of parallel/mesh.py on a
simulated 8-device host platform — and walks the sharding-annotated
programs for the contracts only a mesh can express:

  AU007  large array (> --replicated-min-bytes) placed fully
         REPLICATED across the `clients` axis when a sharded spec
         exists (a dimension divides the axis). At population scale
         the dense client rows are the memory hazard; a replicated
         placement multiplies them by the device count.
  AU008  collective whose payload scales with the client POPULATION
         rather than the cohort: a psum/all_gather moving a
         [num_clients, ...] buffer turns the one-table wire contract
         into population-sized traffic. Detected via the same
         population-sentinel trick as audit.AU004.
  AU009  program input missing an explicit sharding — a dispatch
         operand carrying a single-device (default) placement on a
         multi-device mesh forces GSPMD to reshard it every round.
         The jaxpr-level twin of lint GL007.
  AU010  collective on the wrong LINK CLASS: a `model`-axis collective
         crossing DCN (the make_multihost_client_mesh layout puts
         model innermost exactly so this never happens), or more than
         one table-sized reduction crossing DCN per round (the
         mesh module's one-DCN-all-reduce-per-round invariant,
         previously only a docstring).
  AU011  resharding introduced BETWEEN round stages: a
         sharding_constraint / device_put equation that re-lays-out a
         value another constraint already pinned differently, or
         reshard-class equations present under the mesh that the
         single-device trace of the same program does not contain —
         each is a device-to-device transfer of round state the
         single-device program never pays.

Alongside the rules, every program × mesh gets a deterministic
PER-LINK COLLECTIVE REPORT (analysis/costmodel.collective_cost):
modeled bytes over intra-slice ICI vs inter-slice DCN and the
collective count by kind. The report is diffed exact-match against
the committed ``meshaudit.baseline.json`` and journaled as a
``mesh_audit_digest`` event — the acceptance gate the million-client
refactor lands against (cohort-sized collectives only) and the
before/after table the async/heavy-traffic work will cite.

Meshes audited (all built by the REAL parallel/mesh.py constructors,
so the audit exercises production layout code):

  clients8          1-D `clients` over 8 devices (pure ICI)
  clients4_model2   2-D clients x model, model innermost (pure ICI)
  multislice2       the slice-major multihost layout with an emulated
                    2-slice map (device i -> slice i % 2): the
                    `clients` axis spans DCN, `model` never does

Exit codes (shared with graftaudit, ISSUE 8 satellite): 0 clean,
1 rule violations (AU007-AU011 beyond the baseline), 2 baseline drift
only (link-report mismatch / stale entries) — so CI can distinguish
"the program broke a sharding contract" from "the program changed and
someone must re-commit the baseline".

Import discipline matches analysis/audit: jax imports live inside the
tracing functions; `main` pins JAX_PLATFORMS=cpu and forces the
8-device host platform BEFORE the first jax import.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from commefficient_tpu.analysis.audit import (
    AUDIT_GEOMETRY, AuditBaseline, AuditFinding, audit_configs,
    exit_code, iter_eqns, split_findings, _leaf_names,
)
from commefficient_tpu.analysis.costmodel import (
    CollectiveCost, MeshLinkModel, collective_cost,
)
from commefficient_tpu.analysis.domains import CLIENTS_AXIS, MODEL_AXIS

MESH_RULE_DOCS = {
    "AU007": "large array fully replicated across the `clients` axis "
             "when a sharded spec exists (> --replicated-min-bytes)",
    "AU008": "collective payload scales with the client POPULATION "
             "rather than the cohort",
    "AU009": "program input without an explicit NamedSharding on the "
             "audit mesh (jaxpr-level twin of lint GL007)",
    "AU010": "collective on the wrong link class: model-axis traffic "
             "over DCN, or > 1 table-sized DCN reduction per round",
    "AU011": "resharding between round stages the single-device "
             "program doesn't have (conflicting sharding constraints "
             "/ extra reshard equations under the mesh)",
}

# the population sentinel the mesh workload traces with. Divisible by
# every registered clients-axis size (8 and 4) so init_client_state
# pads nothing and the sentinel survives into the traced shapes
# verbatim; 184 = 8 * 23 collides with no other geometry dimension.
MESH_POPULATION = 184

# scanned-span trip count for the `span` program (small, fixed — the
# per-link report scales linearly with it and the baseline prices it)
SPAN_LEN = 2

# the three single-round treedefs, the two state-motion programs
# (cohort gather / scatter-back — since ISSUE 9 the only programs
# whose operands may carry the population dimension), and the scanned
# span — the full dispatch surface of federated/round.make_train_fn
MESH_PROGRAMS = ("mask_free", "dropout", "dropout_stragglers",
                 "gather", "scatter", "span")


def mesh_programs_for(cfg) -> tuple:
    """Per-config mesh program list: the config's steady-state round
    variants (federated/round.program_variants_for — the screened
    family for ISSUE 16 value-fault configs, the three defaults
    otherwise) plus the family-independent state-motion pair and the
    scanned span."""
    from commefficient_tpu.federated.round import program_variants_for
    return tuple(program_variants_for(cfg)) + ("gather", "scatter",
                                               "span")

# jaxpr equations that re-lay-out an existing value (AU011's
# reshard-class set)
_RESHARD_PRIMITIVES = frozenset({"sharding_constraint", "device_put"})


# ---------------------------------------------------------------------------
# mesh registry


def required_devices() -> int:
    return 8


def build_meshes(names: Optional[Sequence[str]] = None) -> Dict[str, dict]:
    """name -> {"mesh": Mesh, "link": MeshLinkModel, "slices": int}
    for every registered audit mesh (or the `names` subset). Requires
    the 8-device simulated host platform (main() forces it; tests get
    it from conftest)."""
    import jax

    from commefficient_tpu.parallel.mesh import (
        make_client_mesh, make_client_model_mesh,
        make_multihost_client_mesh,
    )

    if len(jax.devices()) < required_devices():
        raise RuntimeError(
            f"graftmesh needs {required_devices()} simulated devices "
            f"(have {len(jax.devices())}); run under XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 (the graftmesh "
            "CLI sets this itself when jax is not yet imported)")

    registry = {
        "clients8": (lambda: make_client_mesh(8), 1),
        "clients4_model2": (lambda: make_client_model_mesh(4, 2), 1),
        "multislice2": (lambda: make_multihost_client_mesh(num_slices=2),
                        2),
    }
    picked = names or list(registry)
    out: Dict[str, dict] = {}
    for name in picked:
        try:
            builder, num_slices = registry[name]
        except KeyError:
            raise KeyError(
                f"unknown audit mesh {name!r}; registered: "
                f"{sorted(registry)}") from None
        mesh = builder()
        out[name] = {"mesh": mesh, "slices": num_slices,
                     "link": mesh_link_model(name, mesh, num_slices)}
    return out


def mesh_link_model(name: str, mesh, num_slices: int) -> MeshLinkModel:
    """Derive the per-axis link-class description from a real Mesh.

    An axis "spans DCN" when walking its devices (other axes pinned at
    coordinate 0) visits more than one slice. On real hardware the
    slice of a device is its `slice_index`; the emulated layout
    (make_multihost_client_mesh(num_slices=N) on single-slice/CPU
    devices) assigns device i -> slice i % N, matching the mesh
    module's own emulation."""
    import numpy as np

    arr = np.asarray(mesh.devices)
    real_slices = {int(getattr(d, "slice_index", 0) or 0)
                   for d in arr.flat}

    def slice_of(dev) -> int:
        if len(real_slices) > 1:
            # real multi-slice topology: the hardware map wins (same
            # precedence rule as make_multihost_client_mesh)
            return int(getattr(dev, "slice_index", 0) or 0)
        if num_slices > 1:
            # emulated slice map: device i -> slice i % N
            return int(dev.id) % num_slices
        return 0

    axes = list(mesh.axis_names)
    sizes = []
    slices = []
    for k, axis in enumerate(axes):
        lane = np.moveaxis(arr, k, 0).reshape(arr.shape[k], -1)[:, 0]
        spanned = len({slice_of(d) for d in lane})
        sizes.append((axis, int(arr.shape[k])))
        slices.append((axis, int(spanned)))
    return MeshLinkModel(name=name, axis_sizes=tuple(sizes),
                         axis_slices=tuple(slices))


# ---------------------------------------------------------------------------
# the mesh workload: the REAL round factory + the REAL multihost
# placement helpers, under each audit mesh


def build_mesh_workload(cfg, mesh):
    """Round handle + mesh-placed operands for one audit config. Every
    operand is constructed by the production placement path —
    init_server_state / init_client_state with the mesh, batch leaves
    through multihost.globalize/shard_rows (FedModel._feed's
    helpers) — so a placement regression in those constructors fires
    AU007/AU009 here rather than on a pod."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from commefficient_tpu.federated.round import (
        RoundBatch, client_state_rows, init_client_state,
        init_server_state, make_train_fn,
    )
    from commefficient_tpu.ops.flat import flatten_params
    from commefficient_tpu.parallel import multihost as mh

    g = AUDIT_GEOMETRY

    def loss_fn(params, batch, mask):
        x, y = batch
        pred = x @ params["w"]
        per_ex = 0.5 * (pred - y) ** 2
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (per_ex * mask).sum() / denom
        return loss, (loss,)

    params = {"w": jnp.zeros(g["D"], jnp.float32)}
    vec, unravel = flatten_params(params)
    handle = make_train_fn(loss_fn, unravel, cfg, mesh)
    server = init_server_state(cfg, vec, mesh=mesh)
    # the tiered config (ISSUE 11) shards its bounded [working_set, D]
    # block over the same clients axis — client_state_rows routes it
    clients = init_client_state(
        cfg, client_state_rows(cfg, MESH_POPULATION), vec, mesh=mesh)
    batch = RoundBatch(
        mh.globalize(mesh, P(), np.arange(g["W"], dtype=np.int32)),
        (mh.shard_rows(mesh, np.zeros((g["W"], g["B"], g["D"]),
                                      np.float32)),
         mh.shard_rows(mesh, np.zeros((g["W"], g["B"]), np.float32))),
        mh.shard_rows(mesh, np.ones((g["W"], g["B"]), np.float32)))
    # the three treedef variants, with the survivor/work operands
    # placed the way FedModel._call_train places them (explicit
    # globalize — round.audit_batch_variants builds host-default
    # operands, which AU009 would rightly flag on a multi-device mesh)
    ones = mh.globalize(mesh, P(), np.ones(g["W"], np.float32))
    half = mh.globalize(mesh, P(),
                        np.full(g["W"], 0.5, np.float32))
    from commefficient_tpu.federated.round import screened_family
    if screened_family(cfg):
        # screened family (ISSUE 16): the poison mask and the traced
        # screen-enable scalar are placed exactly the way the dispatch
        # path places them (globalize, replicated) — host-default
        # operands here would rightly fire AU009
        zeros = mh.globalize(mesh, P(), np.zeros(g["W"], np.float32))
        s_on = mh.globalize(mesh, P(), np.float32(1.0))
        variants = {
            "screened": batch._replace(
                survivors=ones, work=None, poison=zeros, screen=s_on),
            "screened_stragglers": batch._replace(
                survivors=ones, work=half, poison=zeros, screen=s_on),
        }
    else:
        variants = {
            "mask_free": batch._replace(survivors=None, work=None),
            "dropout": batch._replace(survivors=ones, work=None),
            "dropout_stragglers": batch._replace(survivors=ones,
                                                 work=half),
        }
    # the CONCRETE gathered cohort: executed through the production
    # jitted gather (explicit out_shardings), so the round variants'
    # cohort operands carry exactly the placement the dispatch path
    # produces — AU009/AU007 check the real thing
    cohort = handle.gather(clients, batch.client_ids)
    span = RoundBatch(
        mh.globalize(mesh, P(), np.tile(
            np.arange(g["W"], dtype=np.int32), (SPAN_LEN, 1))),
        (mh.shard_rows(mesh, np.zeros((SPAN_LEN, g["W"], g["B"],
                                       g["D"]), np.float32),
                       leading_axes=1),
         mh.shard_rows(mesh, np.zeros((SPAN_LEN, g["W"], g["B"]),
                                      np.float32), leading_axes=1)),
        mh.shard_rows(mesh, np.ones((SPAN_LEN, g["W"], g["B"]),
                                    np.float32), leading_axes=1))
    if screened_family(cfg):
        # the screened span scans the screened treedef: per-round
        # survivor/poison rows plus the per-round screen scalar lane
        span = span._replace(
            survivors=mh.globalize(mesh, P(), np.ones(
                (SPAN_LEN, g["W"]), np.float32)),
            poison=mh.globalize(mesh, P(), np.zeros(
                (SPAN_LEN, g["W"]), np.float32)),
            screen=mh.globalize(mesh, P(), np.ones(
                (SPAN_LEN,), np.float32)))
    lrs = mh.globalize(mesh, P(), np.full((SPAN_LEN,), 0.1, np.float32))
    lr = mh.globalize(mesh, P(), np.float32(0.1))
    key = mh.globalize(mesh, P(),
                       np.asarray(jax.random.PRNGKey(0)))
    return (handle, server, clients, cohort, variants, span, lr, lrs,
            key)


def trace_mesh_program(handle, server, clients, cohort, variants,
                       span, lr, lrs, key, program: str):
    """(ClosedJaxpr, input leaves with names) for one MESH_PROGRAMS
    entry. Input leaves are the CONCRETE mesh-placed operands (AU007 /
    AU009 read their .sharding); the jaxpr is what the per-round jit,
    the state-motion jits, or the scanned span compiles. The round
    variants take the gathered CohortState (ISSUE 9) — their operand
    surface is population-free; the gather/scatter programs are the
    ones carrying the sharded [population, D] blocks."""
    import jax

    if program == "span":
        args = (server, clients, span, lrs, key)
        closed = jax.make_jaxpr(handle.train_rounds)(*args)
        names = (_leaf_names("server", server)
                 + _leaf_names("clients", clients)
                 + _leaf_names("batch", span)
                 + _leaf_names("lr", lrs) + _leaf_names("key", key))
    elif program == "gather":
        # client_ids are identical across variants — take any
        ids = next(iter(variants.values())).client_ids
        args = (clients, ids)
        closed = jax.make_jaxpr(handle.gather_fn)(*args)
        names = (_leaf_names("clients", clients)
                 + _leaf_names("ids", ids))
    elif program == "scatter":
        ids = next(iter(variants.values())).client_ids
        args = (clients, ids, cohort)
        closed = jax.make_jaxpr(handle.scatter_fn)(*args)
        names = (_leaf_names("clients", clients)
                 + _leaf_names("ids", ids)
                 + _leaf_names("cohort", cohort))
    else:
        args = (server, cohort, variants[program], lr, key)
        closed = jax.make_jaxpr(handle.round_step)(*args)
        names = (_leaf_names("server", server)
                 + _leaf_names("cohort", cohort)
                 + _leaf_names("batch", variants[program])
                 + _leaf_names("lr", lr) + _leaf_names("key", key))
    leaves = jax.tree_util.tree_leaves(args)
    return closed, list(zip(names, leaves))


# ---------------------------------------------------------------------------
# rules


def _spec_axes(sharding) -> set:
    """Mesh axis names a NamedSharding's spec actually shards over."""
    spec = getattr(sharding, "spec", None) or ()
    axes = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, str):
            axes.add(entry)
        else:
            axes.update(e for e in entry if isinstance(e, str))
    return axes


def replication_findings(program: str, inputs, mesh,
                         min_bytes: int) -> List[AuditFinding]:
    """AU007 + AU009 over the concrete input operands."""
    from jax.sharding import NamedSharding

    out: List[AuditFinding] = []
    n_clients_axis = dict(
        zip(mesh.axis_names,
            mesh.devices.shape)).get(CLIENTS_AXIS, 1)
    for name, leaf in inputs:
        sharding = getattr(leaf, "sharding", None)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        if not isinstance(sharding, NamedSharding):
            # covers BOTH a committed single-device placement and a
            # bare host array with no .sharding at all — the latter
            # is the most-unplaced case this rule exists to catch
            kind = (type(sharding).__name__ if sharding is not None
                    else "no placement (host array)")
            out.append(AuditFinding(
                program, "AU009",
                f"input `{name}` {list(shape)} carries "
                f"{kind} instead of an explicit "
                "NamedSharding on the audit mesh: GSPMD reshards it on "
                "every dispatch; place it with device_put / globalize "
                "/ shard_rows"))
            continue
        if (nbytes > min_bytes and n_clients_axis > 1
                and CLIENTS_AXIS not in _spec_axes(sharding)
                and any(d >= n_clients_axis and d % n_clients_axis == 0
                        for d in shape)):
            out.append(AuditFinding(
                program, "AU007",
                f"input `{name}` {list(shape)} ({nbytes} bytes) is "
                "fully replicated across the `clients` axis though a "
                "sharded spec exists (a dimension divides the "
                f"{n_clients_axis}-way axis): at population scale this "
                "multiplies the dominant allocation by the device "
                "count — shard it P('clients', ...)"))
    # no set-dedup (audit.forbidden_primitive_findings rationale)
    return sorted(out)


def collective_findings(program: str, cost: CollectiveCost,
                        population: int, table_bytes: int,
                        rounds_per_program: int) -> List[AuditFinding]:
    """AU008 + AU010 over one program's priced collectives."""
    out: List[AuditFinding] = []
    dcn_table_crossings = 0
    for rec in cost.records:
        if any(population in shape for shape in rec.operand_shapes):
            out.append(AuditFinding(
                program, "AU008",
                f"`{rec.kind}` over {list(rec.axes)} moves a "
                f"population-shaped payload {list(rec.operand_shapes)}"
                ": the wire cost scales with num_clients, not the "
                "cohort — gather the sampled rows before the "
                "collective"))
        if rec.crosses_dcn and MODEL_AXIS in rec.axes:
            out.append(AuditFinding(
                program, "AU010",
                f"`{rec.kind}` over the `model` axis crosses DCN: "
                "model-parallel collectives are per-layer traffic and "
                "must stay on intra-slice ICI (model axis innermost — "
                "parallel/mesh.make_multihost_client_mesh)"))
        if rec.crosses_dcn and rec.payload_bytes >= table_bytes:
            dcn_table_crossings += rec.mult
    if dcn_table_crossings > rounds_per_program:
        out.append(AuditFinding(
            program, "AU010",
            f"{dcn_table_crossings} table-sized (>= {table_bytes} B) "
            f"DCN reductions across {rounds_per_program} round(s): the "
            "round contract is ONE compressed all-reduce over DCN per "
            "round (make_multihost_client_mesh invariant) — fold the "
            "extra reduction into the table psum or keep it intra-"
            "slice"))
    return sorted(out)


def _reshard_eqns(closed) -> List[Tuple[str, str, object]]:
    """(primitive, sharding-repr, input var) of every reshard-class
    equation in a program, in walk order."""
    out = []
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name not in _RESHARD_PRIMITIVES:
            continue
        if name == "sharding_constraint":
            spec = repr(eqn.params.get("sharding"))
        else:
            spec = repr(eqn.params.get("devices",
                                       eqn.params.get("device")))
        invar = eqn.invars[0] if eqn.invars else None
        outvar = eqn.outvars[0] if eqn.outvars else None
        out.append((name, spec, invar, outvar))
    return out


def reshard_findings(program: str, closed,
                     baseline_count: Optional[int]) -> List[AuditFinding]:
    """AU011: conflicting constraints within the program, plus
    reshard-class equations the single-device trace doesn't have."""
    out: List[AuditFinding] = []
    eqns = _reshard_eqns(closed)
    pinned: Dict[int, str] = {}
    for name, spec, invar, outvar in eqns:
        if invar is not None and id(invar) in pinned \
                and pinned[id(invar)] != spec:
            out.append(AuditFinding(
                program, "AU011",
                f"`{name}` re-lays-out a value another constraint "
                f"already pinned ({pinned[id(invar)]} -> {spec}): a "
                "device-to-device reshard between round stages — pick "
                "one layout for the value or reshard outside the "
                "round"))
        if outvar is not None:
            pinned[id(outvar)] = spec
    if baseline_count is not None and len(eqns) > baseline_count:
        out.append(AuditFinding(
            program, "AU011",
            f"{len(eqns)} reshard-class equation(s) under the mesh vs "
            f"{baseline_count} in the single-device trace of the same "
            "program: the mesh placement introduced device-to-device "
            "transfers the single-device program doesn't pay"))
    return sorted(out)


# ---------------------------------------------------------------------------
# baseline + report


class MeshBaseline(AuditBaseline):
    """meshaudit.baseline.json: grandfathered violations + the exact
    per-link report {program: {ici_bytes, dcn_bytes,
    dcn_collectives}}. Same exact-match semantics as the audit
    baseline; drift findings carry the MAU006 label so the CLIs can
    map them to exit code 2 (baseline drift) instead of 1 (rule
    violation)."""

    COST_KEY = "links"
    COST_FIELDS = ("ici_bytes", "dcn_bytes", "dcn_collectives")
    DRIFT_RULE = "MAU006"


def mesh_configs(backends: Sequence[str] = ("xla", "pallas")):
    """The audit-config surface, re-populated for the mesh tier: the
    sentinel must divide every registered clients axis so client-state
    rows carry it un-padded."""
    return audit_configs(backends, population=MESH_POPULATION)


def run_mesh_audit(backends: Sequence[str] = ("xla", "pallas"),
                   mesh_names: Optional[Sequence[str]] = None,
                   replicated_min_bytes: int = 1 << 20,
                   dcn_table_bytes: int = 1024,
                   ) -> Tuple[dict, List[AuditFinding]]:
    """Trace every config x mesh x program; return (report, findings).
    Findings carry AU007-AU011; the per-link drift (MAU006) is the
    caller's baseline diff over report["links"]."""
    from commefficient_tpu.parallel.mesh import make_client_mesh

    meshes = build_meshes(mesh_names)
    programs: Dict[str, dict] = {}
    findings: List[AuditFinding] = []
    for cfg_name, cfg in mesh_configs(backends):
        # single-device reshard baseline, shared across meshes: the
        # same program traced on the 1-device mesh (AU011's "the
        # single-device program doesn't have" reference)
        cfg_programs = mesh_programs_for(cfg)
        single = build_mesh_workload(cfg, make_client_mesh(1))
        single_counts = {}
        for program in cfg_programs:
            closed_1, _ = trace_mesh_program(*single, program)
            single_counts[program] = len(_reshard_eqns(closed_1))
        for mesh_name, entry in meshes.items():
            mesh, link = entry["mesh"], entry["link"]
            workload = build_mesh_workload(cfg, mesh)
            for program in cfg_programs:
                prog = f"{cfg_name}/{program}@{mesh_name}"
                closed, inputs = trace_mesh_program(*workload, program)
                cost = collective_cost(closed, link)
                rounds = SPAN_LEN if program == "span" else 1
                findings.extend(replication_findings(
                    prog, inputs, mesh, replicated_min_bytes))
                findings.extend(collective_findings(
                    prog, cost, MESH_POPULATION, dcn_table_bytes,
                    rounds))
                findings.extend(reshard_findings(
                    prog, closed, single_counts[program]))
                programs[prog] = cost.as_dict()
    report = {
        "version": 1,
        "geometry": dict(AUDIT_GEOMETRY, population=MESH_POPULATION,
                         span_len=SPAN_LEN),
        "meshes": {name: entry["link"].as_dict()
                   for name, entry in sorted(meshes.items())},
        "programs": programs,
        "links": {p: {"ici_bytes": d["ici_bytes"],
                      "dcn_bytes": d["dcn_bytes"],
                      "dcn_collectives": d["dcn_collectives"]}
                  for p, d in programs.items()},
    }
    report["digest"] = report_digest(report)
    return report, sorted(findings)


def report_digest(report: dict) -> str:
    """sha256 over the canonical per-link block — the bit-identical-
    across-runs claim is checked on exactly this value."""
    canon = json.dumps({"geometry": report["geometry"],
                        "meshes": report["meshes"],
                        "links": report["links"]},
                       sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def journal_digest(journal_path: str, report: dict,
                   findings_count: int) -> dict:
    """Append the per-link report as a `mesh_audit_digest` event
    (schema checked by telemetry.journal.validate_journal)."""
    from commefficient_tpu.telemetry.journal import append_event
    return append_event(
        journal_path, "mesh_audit_digest",
        digest=report["digest"],
        geometry=report["geometry"],
        meshes=report["meshes"],
        programs=report["links"],
        findings=int(findings_count))


# ---------------------------------------------------------------------------
# CLI (also reachable as `graftaudit --mesh`)


def force_host_devices(n: int = 8) -> None:
    """Arrange for `n` simulated host devices BEFORE the first jax
    import. A no-op when the flag is already present (conftest) or jax
    is already imported (build_meshes then validates the count)."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


# the shared exit-code contract (split_findings / exit_code) lives in
# analysis/audit — tier 2, which this module already depends on — and
# is re-exported here for callers that think in mesh-tier terms


def main(argv: Optional[list] = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    force_host_devices(required_devices())

    from commefficient_tpu.analysis.engine import load_pyproject_tool
    conf = load_pyproject_tool("graftmesh")
    ap = argparse.ArgumentParser(
        prog="graftmesh",
        description="mesh-aware program auditor: replication, "
                    "population-scaling collectives, link-class "
                    "placement, resharding, and the per-link "
                    "ICI/DCN byte baseline (rules AU007-AU011; "
                    "see --list-rules)")
    ap.add_argument("--baseline",
                    default=conf.get("baseline",
                                     "meshaudit.baseline.json"))
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding and skip the link diff")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this audit")
    ap.add_argument("--backends", nargs="*",
                    default=list(conf.get("backends",
                                          ["xla", "pallas"])))
    ap.add_argument("--meshes", nargs="*",
                    default=list(conf.get("meshes", [])) or None,
                    help="subset of the mesh registry to audit")
    ap.add_argument("--replicated-min-bytes", type=int,
                    default=int(conf.get("replicated_min_bytes",
                                         1 << 20)),
                    help="AU007 fires on replicated arrays above this")
    ap.add_argument("--dcn-table-bytes", type=int,
                    default=int(conf.get("dcn_table_bytes", 1024)),
                    help="payload at/above which a DCN reduction "
                         "counts against the once-per-round budget")
    ap.add_argument("--journal", default="",
                    help="append the report to this JSONL run journal "
                         "as a `mesh_audit_digest` event")
    ap.add_argument("--report", action="store_true",
                    help="print the full JSON report to stdout")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-meshes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, doc in sorted(MESH_RULE_DOCS.items()):
            print(f"{code}  {doc}")
        return 0
    if args.list_meshes:
        for name, entry in sorted(build_meshes().items()):
            link = entry["link"]
            print(f"{name}  axes={dict(link.axis_sizes)} "
                  f"dcn_spans={dict(link.axis_slices)}")
        return 0

    for b in args.backends:
        if b not in ("xla", "pallas"):
            print(f"graftmesh: unknown backend {b!r}", file=sys.stderr)
            return 3

    report, findings = run_mesh_audit(
        args.backends, args.meshes,
        replicated_min_bytes=args.replicated_min_bytes,
        dcn_table_bytes=args.dcn_table_bytes)

    if args.write_baseline:
        counts: Dict[Tuple[str, str], int] = {}
        for f in findings:
            counts[(f.program, f.rule)] = counts.get(
                (f.program, f.rule), 0) + 1
        MeshBaseline(
            {k: (n, "TODO: justify or fix") for k, n in counts.items()},
            report["links"]).dump(args.baseline)
        print(f"graftmesh: wrote {len(findings)} grandfathered "
              f"finding(s) + {len(report['links'])} program link "
              f"report(s) to {args.baseline}")
        return 0

    stale: List[str] = []
    if not args.no_baseline:
        baseline = (MeshBaseline.load(args.baseline)
                    if os.path.exists(args.baseline) else
                    MeshBaseline())
        new, stale = baseline.apply_violations(findings)
        drift_findings = baseline.apply_costs(report["links"],
                                              tolerance=0.0)
        findings = sorted(new + drift_findings)

    if args.report:
        print(json.dumps(report, indent=2, sort_keys=True))
    if args.journal:
        journal_digest(args.journal, report, len(findings))

    violations, drift = split_findings(findings)
    for f in findings:
        print(f.render())
    for msg in stale:
        print(f"graftmesh: {msg}")
    rc = exit_code(violations, drift, stale)
    if rc:
        print(f"graftmesh: {len(violations)} violation(s), "
              f"{len(drift)} drift finding(s), {len(stale)} stale "
              f"baseline entr(ies)")
        return rc
    print(f"graftmesh: clean ({len(report['programs'])} program(s) "
          f"across {len(report['meshes'])} mesh(es), digest "
          f"{report['digest'][:12]})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Runtime sanitizers: the dynamic half of graftlint and graftsync.

The static passes (engine/rules, syncaudit) catch what syntax can
prove; these catch what only execution can — armed by the test suite
so the engine's load-bearing runtime contracts are EXECUTED checks,
not prose:

  * `assert_program_count(n)` — a compilation counter around a block.
    ROADMAP's "exactly three traced round programs" (mask-free,
    dropout, dropout+stragglers) becomes `with
    assert_program_count(3): <run all three configs twice>`: a fourth
    program (an accidental retrace from a new treedef, a weak-type
    flip-flop, a shape leak) fails the block. Counting is a pair of
    jax.monitoring listeners (backend-compile durations + compilation-
    cache requests, max of the two — robust whether the compilation
    cache is enabled, disabled, or hitting its persistent store) — no
    monkeypatching, counts executable builds (tracing-cache hits and
    C++ fast-path dispatches are free, as they must be).
  * `forbid_transfers()` — `jax.transfer_guard("disallow")` around a
    block: any IMPLICIT host<->device transfer (an `np.asarray` of a
    device array, a python-scalar operand materialized at dispatch, a
    stray `float()`) raises. Explicit `jax.device_put`/`device_get`
    stay legal — the framework's host boundaries (multihost.globalize
    / gather_host) are deliberately explicit so a guarded round is
    provably sync-free everywhere else.

  * `LockOrderSanitizer` — graftsync's runtime twin (ISSUE 14).
    Installed, it replaces `threading.Lock`/`threading.RLock` with
    recording proxies: every successful acquisition while other
    instrumented locks are held adds a lock-order edge, and
    `assert_acyclic()` at teardown raises `LockOrderError` naming
    the cycle when two threads ever took instrumented locks in
    opposite orders — the dynamic ABBA check over orders the static
    SY002 graph cannot see (locks reached through aliases, orders
    composed across modules at runtime). Instrumentation is by
    OBJECT, so the RLock re-entrancy idiom adds no self-edges, and
    `queue.Queue`'s internal mutex/conditions are instrumented for
    free (queue looks `threading.Lock` up dynamically).
  * `interleaving_stress()` — deterministic delay injection at the
    writer-queue handoffs (`queue.Queue.put`/`get`): a counter-driven
    (never random — replayable) sub-millisecond stagger that widens
    the producer/drain race windows the bounded-queue writers must
    tolerate. tier1.sh arms both over the pipeline/statetier/
    controlplane suites via the `CCTPU_SYNC_SANITIZE=1` autouse
    fixture (tests/conftest.py).

  * `NumericSanitizer` — graftnum's runtime twin (ISSUE 18).
    Installed, it wraps `telemetry.metrics.named` (the ONE host
    boundary every exported round metric crosses) in a post-dispatch
    finite-guard: any NaN/inf reaching an export raises
    `NumericError` naming the metric — the dynamic check behind the
    static NU001 lattice's one assumption (that a `where` guard's
    predicate is semantically sufficient). `replay_drill(fn, *args)`
    dispatches a traced program twice on identical operands and
    asserts bitwise equality leaf by leaf — the executable form of
    the NU004 crash->resume contract. tier1.sh re-runs the
    valuefaults/byzantine suites with the guard armed via the
    `CCTPU_NUM_SANITIZE=1` autouse fixture (tests/conftest.py).

The `sanitize` pytest fixture (tests/conftest.py) hands tests the
program-count/transfer pair; `lock_sanitizer` hands them an
installed LockOrderSanitizer; `num_sanitizer` an installed
NumericSanitizer.
"""
from __future__ import annotations

import contextlib
import itertools
import math
import queue as _queue
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax

# Two redundant per-program signals, counted independently; the block
# count is their max. Each fires once per distinct executable and
# never on tracing-cache hits or C++ fast-path dispatches:
#   * backend_compile_duration — one per XLA backend compile,
#     unconditionally (fires even with the compilation cache disabled,
#     where the cache-request event below never records);
#   * compile_requests_use_cache — one per compile request when the
#     cache is consulted (covers persistent-cache HITS, where a
#     distinct program loads without a backend compile).
_COMPILE_EVENTS = frozenset({
    "/jax/compilation_cache/compile_requests_use_cache",
})
_COMPILE_DURATION_EVENTS = frozenset({
    "/jax/core/compile/backend_compile_duration",
})

_counter = {"requests": 0, "backend": 0, "installed": False}

# external compile subscribers (telemetry journal): called with
# (event_name, duration_seconds) once per backend compile. Fed from the
# DURATION listener only — it fires unconditionally per executable
# build, while the cache-request event double-counts when both fire.
_compile_subscribers: list = []


def add_compile_listener(cb) -> None:
    """Subscribe `cb(event_name, duration_s)` to backend-compile
    events (the telemetry journal uses this to record every XLA
    compile, and to flag steady-state recompiles). Idempotent per
    callback object."""
    _ensure_listener()
    if cb not in _compile_subscribers:
        _compile_subscribers.append(cb)


def remove_compile_listener(cb) -> None:
    try:
        _compile_subscribers.remove(cb)
    except ValueError:
        pass


def _on_event(event: str, **kw) -> None:
    if event in _COMPILE_EVENTS:
        _counter["requests"] += 1


def _on_event_duration(event: str, duration: float, **kw) -> None:
    if event in _COMPILE_DURATION_EVENTS:
        _counter["backend"] += 1
        for cb in list(_compile_subscribers):
            cb(event, duration)


def _ensure_listener() -> None:
    if not _counter["installed"]:
        jax.monitoring.register_event_listener(_on_event)
        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration)
        _counter["installed"] = True


class ProgramCount:
    """Result handle of `count_programs`: `.count` is the number of
    programs compiled inside the block (live-updating during it)."""

    def __init__(self, start_requests: int, start_backend: int):
        self._start_requests = start_requests
        self._start_backend = start_backend

    @property
    def count(self) -> int:
        return max(_counter["requests"] - self._start_requests,
                   _counter["backend"] - self._start_backend)


@contextlib.contextmanager
def count_programs():
    """Count XLA executables built inside the block."""
    _ensure_listener()
    yield ProgramCount(_counter["requests"], _counter["backend"])


@contextlib.contextmanager
def assert_program_count(n: int):
    """Assert EXACTLY `n` programs compile inside the block.

    Build every operand (device arrays, keys, lr scalars) BEFORE the
    block: eager jnp ops compile their own tiny programs and would
    inflate the count. A block observing 0 when n > 0 usually means the
    workload was warmed up beforehand — this sanitizer wants the cold
    calls inside."""
    with count_programs() as c:
        yield c
    got = c.count
    if got != n:
        if got > n:
            why = ("an extra program means an accidental retrace (new "
                   "treedef/shape/dtype or weak-type flip) — the "
                   "three-programs contract of federated/round.py caps "
                   "dispatch cost")
        else:
            why = ("fewer means the block was pre-warmed or the "
                   "workload never ran")
        raise AssertionError(
            f"program-count contract violated: expected exactly {n} "
            f"compiled program(s) in this block, observed {got}; {why} "
            "(see analysis/runtime.py)")


@contextlib.contextmanager
def forbid_transfers():
    """Disallow implicit host<->device transfers inside the block
    (explicit jax.device_put / jax.device_get remain legal)."""
    with jax.transfer_guard("disallow"):
        yield


class Sanitizer:
    """What the `sanitize` pytest fixture hands a test."""

    count_programs = staticmethod(count_programs)
    assert_program_count = staticmethod(assert_program_count)
    forbid_transfers = staticmethod(forbid_transfers)


# ---------------------------------------------------------------------------
# LockOrderSanitizer — graftsync's runtime twin (ISSUE 14)


class LockOrderError(AssertionError):
    """The observed lock-acquisition graph contains a cycle: two
    threads took instrumented locks in opposite orders at least once
    — a latent ABBA deadlock that only needs worse timing."""


class _SanitizedLock:
    """Proxy around a real Lock/RLock that reports acquisitions to
    its owning sanitizer. Unknown attributes (RLock's
    `_release_save`/`_acquire_restore`/`_is_owned`, used by
    Condition) delegate to the wrapped lock — Condition then drives
    the REAL lock for its wait dance, which keeps the proxy's held
    bookkeeping aligned with the logical critical section."""

    def __init__(self, san: "LockOrderSanitizer", inner, node: str):
        self._san = san
        self._inner = inner
        self._node = node

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._san._note_acquire(self)
        return ok

    def release(self) -> None:
        self._san._note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._inner, name)


class LockOrderSanitizer:
    """Record per-thread lock-acquisition edges; assert the global
    graph acyclic at teardown.

    `install()` swaps `threading.Lock`/`threading.RLock` for proxy
    factories (locks created BEFORE install stay uninstrumented —
    the fixture installs before constructing the objects under
    test); `uninstall()` restores the factories and freezes edge
    recording (already-created proxies keep working, they just stop
    reporting). Nodes are per lock OBJECT — `file:line#serial` of
    the creation site — so two queues' mutexes never alias into one
    node (the false-positive class a lockdep-style per-class graph
    would hit), and an RLock re-acquisition adds no self-edge.
    Deterministic given a deterministic schedule: edges carry the
    acquiring thread and site for the report, not timestamps."""

    def __init__(self):
        # real (uninstrumented) lock: the sanitizer must never
        # instrument its own bookkeeping
        self._graph_lock = threading.Lock()
        # (outer node, inner node) -> (thread name, "file:line" of
        # the inner acquisition)
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._held = threading.local()
        self._serial = itertools.count()
        self._active = False
        self._orig: Optional[tuple] = None

    # ---------------- factory patching --------------------------------
    @staticmethod
    def _site(depth: int = 2) -> str:
        frame = sys._getframe(depth)
        # walk out of this module so the node names the USER's
        # creation/acquisition site, not the proxy internals
        while frame is not None and frame.f_globals.get(
                "__name__") == __name__:
            frame = frame.f_back
        if frame is None:
            return "<unknown>"
        return f"{frame.f_code.co_filename}:{frame.f_lineno}"

    def _make(self, ctor):
        def factory():
            node = f"{self._site()}#{next(self._serial)}"
            return _SanitizedLock(self, ctor(), node)
        return factory

    def install(self) -> None:
        if self._orig is not None:
            return
        self._orig = (threading.Lock, threading.RLock)
        threading.Lock = self._make(self._orig[0])
        threading.RLock = self._make(self._orig[1])
        self._active = True

    def uninstall(self) -> None:
        if self._orig is None:
            return
        threading.Lock, threading.RLock = self._orig
        self._orig = None
        self._active = False

    def __enter__(self):
        self.install()
        return self

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # ---------------- recording ---------------------------------------
    def _stack(self) -> List[_SanitizedLock]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _note_acquire(self, lock: _SanitizedLock) -> None:
        stack = self._stack()
        if self._active:
            for held in stack:
                if held is lock:
                    continue  # RLock re-entrancy: no self-edge
                key = (held._node, lock._node)
                if key not in self._edges:
                    with self._graph_lock:
                        self._edges.setdefault(
                            key, (threading.current_thread().name,
                                  self._site(3)))
        stack.append(lock)

    def _note_release(self, lock: _SanitizedLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    # ---------------- verdict -----------------------------------------
    def edges(self) -> Dict[Tuple[str, str], Tuple[str, str]]:
        with self._graph_lock:
            return dict(self._edges)

    def find_cycle(self) -> Optional[List[str]]:
        """One cycle in the observed acquisition graph, or None —
        the same cycle definition the static SY002 rule uses
        (engine.find_cycles)."""
        from commefficient_tpu.analysis.engine import (
            edges_to_graph, find_cycles,
        )
        cycles = find_cycles(edges_to_graph(self.edges()))
        return cycles[0] if cycles else None

    def assert_acyclic(self) -> None:
        cyc = self.find_cycle()
        if cyc is None:
            return
        edges = self.edges()
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            thread, site = edges[(a, b)]
            sites.append(f"  {a} -> {b}  (thread {thread!r} at {site})")
        raise LockOrderError(
            "lock-order cycle observed — two threads acquired these "
            "locks in opposite orders at least once (ABBA deadlock "
            "given worse timing):\n" + "\n".join(sites)
            + "\npick ONE global acquisition order (graftsync SY002 "
            "checks the static `with` nesting; this caught an order "
            "composed at runtime)")


# ---------------------------------------------------------------------------
# NumericSanitizer — graftnum's runtime twin (ISSUE 18)


class NumericError(AssertionError):
    """A non-finite value crossed a guarded numeric boundary (an
    exported round metric, a replay-drill mismatch): the static
    graftnum lattice proved the shipped guards are selects, this
    caught a predicate that was not semantically sufficient — or a
    program that did not replay bit-identically."""


class NumericSanitizer:
    """Scoped post-dispatch numeric guard.

    `install()` wraps `telemetry.metrics.named` — the single host
    boundary every exported round-metric vector crosses (the round
    engine, the telemetry writers, and bench all call it by module
    attribute) — so any NaN/inf that survived the on-device guards
    raises `NumericError` at the EXPORT, naming the metric, instead
    of poisoning a CSV three stages later. `uninstall()` restores the
    original; both are idempotent. `.checked` counts guarded vectors
    (a zero after a drill means the guard never saw traffic — arm it
    before the workload, like the program counter).

    `replay_drill(fn, *args, **kwargs)` is the NU004 contract made
    executable: dispatch `fn` twice on the SAME operands and assert
    the results bitwise-identical leaf by leaf (bytes of the
    materialized arrays — NaNs compare equal by representation, so a
    deterministic NaN is replay-clean, as the crash->resume contract
    requires). Returns the first call's result."""

    def __init__(self):
        self._orig = None
        self.checked = 0

    # ---------------- metric finite-guard ------------------------------
    def _guarded(self, orig):
        def named(vec):
            out = orig(vec)
            self.checked += 1
            bad = {k: v for k, v in out.items()
                   if not math.isfinite(v)}
            if bad:
                raise NumericError(
                    "non-finite round metric(s) exported: "
                    + ", ".join(f"{k}={v}" for k, v in
                                sorted(bad.items()))
                    + " — a NaN/inf survived the on-device admission "
                    "guards (graftnum NU001/NU003 prove the guards "
                    "are selects; this predicate was not sufficient "
                    "— see analysis/runtime.py)")
            return out
        return named

    def install(self) -> None:
        from commefficient_tpu.telemetry import metrics as tmetrics
        if self._orig is not None:
            return
        self._orig = tmetrics.named
        tmetrics.named = self._guarded(self._orig)

    def uninstall(self) -> None:
        from commefficient_tpu.telemetry import metrics as tmetrics
        if self._orig is None:
            return
        tmetrics.named = self._orig
        self._orig = None

    def __enter__(self):
        self.install()
        return self

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # ---------------- determinism drill --------------------------------
    @staticmethod
    def assert_finite(tree, where: str = "value") -> None:
        """Raise NumericError if any float leaf of `tree` holds a
        NaN/inf (non-float and zero-size leaves pass)."""
        import numpy as np
        for i, leaf in enumerate(jax.tree.leaves(tree)):
            arr = np.asarray(jax.device_get(leaf))
            if arr.dtype.kind != "f" or not arr.size:
                continue
            if not np.isfinite(arr).all():
                n = int((~np.isfinite(arr)).sum())
                raise NumericError(
                    f"non-finite values at {where} (leaf {i}): "
                    f"{n}/{arr.size} element(s) NaN/inf")

    @staticmethod
    def replay_drill(fn, *args, **kwargs):
        import numpy as np
        first = fn(*args, **kwargs)
        second = fn(*args, **kwargs)
        la = jax.tree.leaves(first)
        lb = jax.tree.leaves(second)
        for i, (a, b) in enumerate(zip(la, lb)):
            ba = np.asarray(jax.device_get(a)).tobytes()
            bb = np.asarray(jax.device_get(b)).tobytes()
            if ba != bb:
                raise NumericError(
                    f"replay divergence: leaf {i} of {len(la)} "
                    "differs bitwise between two dispatches on "
                    "identical operands — the crash->resume "
                    "bit-exactness contract (graftnum NU004) does "
                    "not hold for this program")
        return first


@contextlib.contextmanager
def interleaving_stress(delay: float = 0.0005, period: int = 3):
    """Deterministically stagger writer-queue handoffs: every
    `queue.Queue.put`/`get` sleeps `(i % period) * delay` first, `i`
    a shared counter — so producer/drain interleavings that need an
    unlucky scheduler to collide are collided ON PURPOSE, every run,
    with no randomness (a failure under stress replays). The delays
    are host-side only and orders of magnitude below the drain
    timeouts, so semantics (FIFO order, bounded back-pressure, drain
    completeness) are untouched — only the timing is hostile."""
    counter = itertools.count()
    orig_put, orig_get = _queue.Queue.put, _queue.Queue.get

    def put(self, *args, **kwargs):
        time.sleep((next(counter) % period) * delay)
        return orig_put(self, *args, **kwargs)

    def get(self, *args, **kwargs):
        time.sleep((next(counter) % period) * delay)
        return orig_get(self, *args, **kwargs)

    _queue.Queue.put = put
    _queue.Queue.get = get
    try:
        yield
    finally:
        _queue.Queue.put = orig_put
        _queue.Queue.get = orig_get

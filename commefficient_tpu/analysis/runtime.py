"""Runtime sanitizers: the dynamic half of graftlint.

The static pass (engine/rules) catches what syntax can prove; these
context managers catch what only execution can — armed by the test
suite so the round engine's two load-bearing runtime contracts are
EXECUTED checks, not prose:

  * `assert_program_count(n)` — a compilation counter around a block.
    ROADMAP's "exactly three traced round programs" (mask-free,
    dropout, dropout+stragglers) becomes `with
    assert_program_count(3): <run all three configs twice>`: a fourth
    program (an accidental retrace from a new treedef, a weak-type
    flip-flop, a shape leak) fails the block. Counting is a pair of
    jax.monitoring listeners (backend-compile durations + compilation-
    cache requests, max of the two — robust whether the compilation
    cache is enabled, disabled, or hitting its persistent store) — no
    monkeypatching, counts executable builds (tracing-cache hits and
    C++ fast-path dispatches are free, as they must be).
  * `forbid_transfers()` — `jax.transfer_guard("disallow")` around a
    block: any IMPLICIT host<->device transfer (an `np.asarray` of a
    device array, a python-scalar operand materialized at dispatch, a
    stray `float()`) raises. Explicit `jax.device_put`/`device_get`
    stay legal — the framework's host boundaries (multihost.globalize
    / gather_host) are deliberately explicit so a guarded round is
    provably sync-free everywhere else.

The `sanitize` pytest fixture (tests/conftest.py) hands tests a
`Sanitizer` exposing both.
"""
from __future__ import annotations

import contextlib

import jax

# Two redundant per-program signals, counted independently; the block
# count is their max. Each fires once per distinct executable and
# never on tracing-cache hits or C++ fast-path dispatches:
#   * backend_compile_duration — one per XLA backend compile,
#     unconditionally (fires even with the compilation cache disabled,
#     where the cache-request event below never records);
#   * compile_requests_use_cache — one per compile request when the
#     cache is consulted (covers persistent-cache HITS, where a
#     distinct program loads without a backend compile).
_COMPILE_EVENTS = frozenset({
    "/jax/compilation_cache/compile_requests_use_cache",
})
_COMPILE_DURATION_EVENTS = frozenset({
    "/jax/core/compile/backend_compile_duration",
})

_counter = {"requests": 0, "backend": 0, "installed": False}

# external compile subscribers (telemetry journal): called with
# (event_name, duration_seconds) once per backend compile. Fed from the
# DURATION listener only — it fires unconditionally per executable
# build, while the cache-request event double-counts when both fire.
_compile_subscribers: list = []


def add_compile_listener(cb) -> None:
    """Subscribe `cb(event_name, duration_s)` to backend-compile
    events (the telemetry journal uses this to record every XLA
    compile, and to flag steady-state recompiles). Idempotent per
    callback object."""
    _ensure_listener()
    if cb not in _compile_subscribers:
        _compile_subscribers.append(cb)


def remove_compile_listener(cb) -> None:
    try:
        _compile_subscribers.remove(cb)
    except ValueError:
        pass


def _on_event(event: str, **kw) -> None:
    if event in _COMPILE_EVENTS:
        _counter["requests"] += 1


def _on_event_duration(event: str, duration: float, **kw) -> None:
    if event in _COMPILE_DURATION_EVENTS:
        _counter["backend"] += 1
        for cb in list(_compile_subscribers):
            cb(event, duration)


def _ensure_listener() -> None:
    if not _counter["installed"]:
        jax.monitoring.register_event_listener(_on_event)
        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration)
        _counter["installed"] = True


class ProgramCount:
    """Result handle of `count_programs`: `.count` is the number of
    programs compiled inside the block (live-updating during it)."""

    def __init__(self, start_requests: int, start_backend: int):
        self._start_requests = start_requests
        self._start_backend = start_backend

    @property
    def count(self) -> int:
        return max(_counter["requests"] - self._start_requests,
                   _counter["backend"] - self._start_backend)


@contextlib.contextmanager
def count_programs():
    """Count XLA executables built inside the block."""
    _ensure_listener()
    yield ProgramCount(_counter["requests"], _counter["backend"])


@contextlib.contextmanager
def assert_program_count(n: int):
    """Assert EXACTLY `n` programs compile inside the block.

    Build every operand (device arrays, keys, lr scalars) BEFORE the
    block: eager jnp ops compile their own tiny programs and would
    inflate the count. A block observing 0 when n > 0 usually means the
    workload was warmed up beforehand — this sanitizer wants the cold
    calls inside."""
    with count_programs() as c:
        yield c
    got = c.count
    if got != n:
        if got > n:
            why = ("an extra program means an accidental retrace (new "
                   "treedef/shape/dtype or weak-type flip) — the "
                   "three-programs contract of federated/round.py caps "
                   "dispatch cost")
        else:
            why = ("fewer means the block was pre-warmed or the "
                   "workload never ran")
        raise AssertionError(
            f"program-count contract violated: expected exactly {n} "
            f"compiled program(s) in this block, observed {got}; {why} "
            "(see analysis/runtime.py)")


@contextlib.contextmanager
def forbid_transfers():
    """Disallow implicit host<->device transfers inside the block
    (explicit jax.device_put / jax.device_get remain legal)."""
    with jax.transfer_guard("disallow"):
        yield


class Sanitizer:
    """What the `sanitize` pytest fixture hands a test."""

    count_programs = staticmethod(count_programs)
    assert_program_count = staticmethod(assert_program_count)
    forbid_transfers = staticmethod(forbid_transfers)

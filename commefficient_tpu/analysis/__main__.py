"""CLI: ``python -m commefficient_tpu.analysis [paths...]``.

Exit codes: 0 clean (after baseline), 1 violations or stale baseline
or lint errors, 2 usage errors. Configuration lives in pyproject.toml
under ``[tool.graftlint]`` (paths, baseline, exclude) — flags override.
"""
from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Optional

from commefficient_tpu.analysis.engine import (
    Baseline, LintError, lint_paths,
)
from commefficient_tpu.analysis.rules import RULE_DOCS


def _load_pyproject_config(start: str = ".") -> dict:
    """[tool.graftlint] from the nearest pyproject.toml, via tomllib/
    tomli when available, else a minimal line parser good enough for
    the flat strings-and-string-lists section this tool defines."""
    path = os.path.join(start, "pyproject.toml")
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        try:
            import tomllib  # py311+
        except ImportError:
            import tomli as tomllib
        return tomllib.loads(text).get("tool", {}).get("graftlint", {})
    except ImportError:
        pass
    m = re.search(r"^\[tool\.graftlint\]\s*$(.*?)(?=^\[|\Z)", text,
                  re.M | re.S)
    if not m:
        return {}
    out: dict = {}
    for line in m.group(1).splitlines():
        kv = re.match(r"\s*(\w+)\s*=\s*(.+?)\s*$", line)
        if not kv:
            continue
        key, val = kv.group(1), kv.group(2)
        if val.startswith("["):
            out[key] = re.findall(r'"([^"]*)"', val)
        elif val.startswith('"'):
            out[key] = val.strip('"')
    return out


def main(argv: Optional[list] = None) -> int:
    conf = _load_pyproject_config()
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="trace-safety static analysis for the round engine "
                    "(rules GL001-GL006; see --list-rules)")
    ap.add_argument("paths", nargs="*",
                    default=conf.get("paths", ["commefficient_tpu"]),
                    help="files/directories to lint")
    ap.add_argument("--baseline", default=conf.get(
        "baseline", "graftlint.baseline.json"),
        help="baseline file of grandfathered hits")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every hit, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current tree")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, doc in sorted(RULE_DOCS.items()):
            print(f"{code}  {doc}")
        return 0

    for p in args.paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    try:
        violations = lint_paths(args.paths,
                                exclude=conf.get("exclude", ()))
    except LintError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 1

    if args.write_baseline:
        Baseline.from_violations(violations).dump(args.baseline)
        print(f"graftlint: wrote {len(violations)} grandfathered hit(s) "
              f"to {args.baseline}")
        return 0

    baseline = Baseline()
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = Baseline.load(args.baseline)
    new, stale = baseline.apply(violations)

    for v in new:
        print(v.render())
    for msg in stale:
        print(f"graftlint: {msg}")
    n_files = len(set(v.path for v in violations))
    if new or stale:
        print(f"graftlint: {len(new)} violation(s)"
              + (f", {len(stale)} baseline problem(s)" if stale else ""))
        return 1
    grandfathered = len(violations)
    print("graftlint: clean"
          + (f" ({grandfathered} grandfathered hit(s) in {n_files} "
             f"file(s) — see {args.baseline})" if grandfathered else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())

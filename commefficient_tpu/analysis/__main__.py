"""CLI: ``python -m commefficient_tpu.analysis [paths...]``.

Exit codes: 0 clean (after baseline), 1 violations or stale baseline
or lint errors, 2 usage errors. Configuration lives in pyproject.toml
under ``[tool.graftlint]`` (paths, baseline, exclude) — flags override.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from commefficient_tpu.analysis.engine import (
    Baseline, LintError, lint_paths, load_pyproject_tool,
)
from commefficient_tpu.analysis.rules import RULE_DOCS


def main(argv: Optional[list] = None) -> int:
    conf = load_pyproject_tool("graftlint")
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="trace-safety static analysis for the round engine "
                    "(rules GL001-GL012; see --list-rules)")
    ap.add_argument("paths", nargs="*",
                    default=conf.get("paths", ["commefficient_tpu"]),
                    help="files/directories to lint")
    ap.add_argument("--baseline", default=conf.get(
        "baseline", "graftlint.baseline.json"),
        help="baseline file of grandfathered hits")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every hit, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current tree")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, doc in sorted(RULE_DOCS.items()):
            print(f"{code}  {doc}")
        return 0

    for p in args.paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    try:
        violations = lint_paths(args.paths,
                                exclude=conf.get("exclude", ()))
    except LintError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 1

    if args.write_baseline:
        Baseline.from_violations(violations).dump(args.baseline)
        print(f"graftlint: wrote {len(violations)} grandfathered hit(s) "
              f"to {args.baseline}")
        return 0

    baseline = Baseline()
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = Baseline.load(args.baseline)
    new, stale = baseline.apply(violations)

    for v in new:
        print(v.render())
    for msg in stale:
        print(f"graftlint: {msg}")
    n_files = len(set(v.path for v in violations))
    if new or stale:
        print(f"graftlint: {len(new)} violation(s)"
              + (f", {len(stale)} baseline problem(s)" if stale else ""))
        return 1
    grandfathered = len(violations)
    print("graftlint: clean"
          + (f" ({grandfathered} grandfathered hit(s) in {n_files} "
             f"file(s) — see {args.baseline})" if grandfathered else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""graftlint engine: parse, run rules, apply suppressions + baseline.

Deliberately jax-free (pure ``ast`` + stdlib) so the pass runs in any
environment — CI boxes without accelerators, pre-commit hooks, the
tier-1 recipe. Rule logic lives in `rules`; this module owns the
mechanics every rule shares:

  * per-line suppressions — ``# graftlint: disable=GL001[,GL002]`` on
    the reported line silences those rules there (a justification after
    ``--`` is conventional and encouraged);
  * the BASELINE file — JSON grandfathering existing hits per
    (path, rule) with a justification, so new violations fail CI while
    documented legacy ones don't. The baseline must match the tree
    EXACTLY: a fixed violation leaves a stale entry behind, and the
    engine reports staleness as an error too, so the baseline can only
    shrink deliberately (regenerate with ``--write-baseline``).
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")


def load_pyproject_tool(tool: str, start: str = ".") -> dict:
    """``[tool.<tool>]`` from the nearest pyproject.toml — shared by
    the graftlint and graftaudit CLIs. Via tomllib/tomli when
    available, else a minimal line parser good enough for the flat
    strings / string-lists / numbers these tools define."""
    path = os.path.join(start, "pyproject.toml")
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        try:
            import tomllib  # py311+
        except ImportError:
            import tomli as tomllib
        return tomllib.loads(text).get("tool", {}).get(tool, {})
    except ImportError:
        pass
    m = re.search(r"^\[tool\.%s\]\s*$(.*?)(?=^\[|\Z)" % re.escape(tool),
                  text, re.M | re.S)
    if not m:
        return {}
    out: dict = {}
    for line in m.group(1).splitlines():
        kv = re.match(r"\s*(\w+)\s*=\s*(.+?)\s*$", line)
        if not kv:
            continue
        key, val = kv.group(1), kv.group(2)
        if val.startswith("["):
            out[key] = re.findall(r'"([^"]*)"', val)
        elif val.startswith('"'):
            out[key] = val.strip('"')
        else:
            try:
                out[key] = float(val) if "." in val else int(val)
            except ValueError:
                pass
    return out


@dataclasses.dataclass(frozen=True, order=True)
class Violation:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: " \
               f"{self.rule} {self.message}"


class LintError(RuntimeError):
    """A file could not be linted (unreadable / syntax error)."""


def _suppressions(source: str) -> Dict[int, set]:
    out: Dict[int, set] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip().upper() for r in m.group(1).split(",")
                      if r.strip()}
    return out


def lint_source(path: str, source: str,
                rules: Optional[Dict] = None) -> List[Violation]:
    """Lint one file's source. `path` is used for reporting only."""
    from commefficient_tpu.analysis.rules import ALL_RULES, ModuleInfo
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        raise LintError(f"{path}: syntax error: {e}") from e
    module = ModuleInfo(path, source, tree)
    suppressed = _suppressions(source)
    out: List[Violation] = []
    for code, check in (rules or ALL_RULES).items():
        for v in check(module):
            if v.rule in suppressed.get(v.line, ()):
                continue
            out.append(v)
    return sorted(set(out))


def iter_python_files(paths: Sequence[str],
                      exclude: Sequence[str] = ()) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for f in sorted(files):
                if not f.endswith(".py"):
                    continue
                full = os.path.join(root, f)
                rel = full.replace(os.sep, "/")
                if any(pat in rel for pat in exclude):
                    continue
                yield full


def lint_paths(paths: Sequence[str],
               exclude: Sequence[str] = ()) -> List[Violation]:
    out: List[Violation] = []
    for path in iter_python_files(paths, exclude):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(path).replace(os.sep, "/")
        out.extend(lint_source(rel, source))
    return sorted(out)


# ---------------------------------------------------------------------------
# graph utilities shared by the concurrency tiers


def find_cycles(graph: Dict[str, Sequence[str]]) -> List[List[str]]:
    """Distinct cycles in a directed graph ({node: successors}),
    each as [a, b, ..., a], deduped by node SET (one report per
    lock-order cycle however many entry points reach it). Color-
    marking DFS over sorted nodes, so the result is deterministic.
    Shared by graftsync's static lock-order rule (SY002) and the
    runtime LockOrderSanitizer — one cycle definition, two
    enforcement points."""
    cycles: List[List[str]] = []
    seen: set = set()
    state: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(v: str) -> None:
        state[v] = 1
        stack.append(v)
        for w in sorted(graph.get(v, ())):
            if state.get(w, 0) == 0:
                dfs(w)
            elif state.get(w) == 1:
                cyc = stack[stack.index(w):] + [w]
                canon = tuple(sorted(cyc[:-1]))
                if canon not in seen:
                    seen.add(canon)
                    cycles.append(cyc)
        stack.pop()
        state[v] = 2

    for v in sorted(graph):
        if state.get(v, 0) == 0:
            dfs(v)
    return cycles


def edges_to_graph(edges) -> Dict[str, List[str]]:
    """(a, b) edge keys -> the {node: successors} map find_cycles
    takes (isolated successors included so every node is a key)."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    return graph


# ---------------------------------------------------------------------------
# baseline


class Baseline:
    """Grandfathered hits: {(path, rule): (count, justification)}."""

    def __init__(self, entries: Optional[Dict[Tuple[str, str],
                                              Tuple[int, str]]] = None):
        self.entries = dict(entries or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        entries = {}
        for e in raw.get("entries", ()):
            entries[(e["path"], e["rule"])] = (
                int(e["count"]), e.get("justification", ""))
        return cls(entries)

    @classmethod
    def from_violations(cls, violations: Sequence[Violation]) -> "Baseline":
        counts: Dict[Tuple[str, str], int] = {}
        for v in violations:
            counts[(v.path, v.rule)] = counts.get((v.path, v.rule), 0) + 1
        return cls({k: (n, "TODO: justify or fix")
                    for k, n in counts.items()})

    def dump(self, path: str) -> None:
        entries = [
            {"path": p, "rule": r, "count": n, "justification": j}
            for (p, r), (n, j) in sorted(self.entries.items())
        ]
        text = json.dumps({"version": 1, "entries": entries}, indent=2)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        os.replace(tmp, path)

    def apply(self, violations: Sequence[Violation]
              ) -> Tuple[List[Violation], List[str]]:
        """Split a scan against the baseline. Returns (new_violations,
        stale_messages): a (path, rule) group with MORE hits than its
        entry surfaces the overflow as new violations (most-recent
        lines first would be arbitrary — all are reported); a group
        with FEWER hits than its entry is stale (the tree improved:
        shrink the baseline so the win is locked in)."""
        by_key: Dict[Tuple[str, str], List[Violation]] = {}
        for v in violations:
            by_key.setdefault((v.path, v.rule), []).append(v)
        new: List[Violation] = []
        stale: List[str] = []
        for key, vs in sorted(by_key.items()):
            allowed = self.entries.get(key, (0, ""))[0]
            if len(vs) > allowed:
                # overflow: the whole group is re-reported (line
                # numbers churn, so WHICH hits are new is unknowable)
                new.extend(vs)
        for key, (count, _) in sorted(self.entries.items()):
            have = len(by_key.get(key, ()))
            if have < count:
                stale.append(
                    f"stale baseline entry {key[0]} {key[1]}: baseline "
                    f"grandfathers {count}, tree has {have} — "
                    "regenerate with --write-baseline to lock in the fix")
            elif have > count and count > 0:
                # overflow groups were fully re-reported above; note why
                stale.append(
                    f"baseline entry {key[0]} {key[1]} exceeded: "
                    f"grandfathers {count}, tree has {have}")
        return new, stale

"""Static per-primitive cost model over jaxprs: FLOPs + HBM bytes.

The hardware-independent half of the PERF story (ISSUE 7): PR 6's
kernel/perf claims are TPU-pending because the tunnel is down, but the
PROGRAM is fully known at trace time — so this module walks a
ClosedJaxpr and prices every equation with a deterministic analytic
model. The absolute numbers are coarse (see the honesty notes below);
what the auditor gates on is their STABILITY: the same config must
price to the identical integer on every trace, so any drift in the
committed `audit.baseline.json` is a real program change someone must
look at — the static stand-in for a bench regression gate.

Model (deliberately simple, deliberately documented):

  * FLOPs — `dot_general` and `conv_general_dilated` get the exact
    2·M·N·K count from their dimension numbers; `sort`/`top_k` are
    priced as comparison networks (n·ceil(log2 n), n·ceil(log2 k));
    reductions cost their operand size; everything else costs its
    output size (one op per output element — transcendentals are
    undercounted by a small constant factor, uniformly, which cancels
    in a regression diff).
  * HBM bytes — every equation is priced as if un-fused: operand bytes
    in + result bytes out. Real XLA fuses elementwise chains, so this
    is an UPPER BOUND on traffic, not a prediction — but a new
    intermediate buffer shows up in it immediately, which is the
    regression class (an accidental [D]-materialization) the gate
    exists to catch.
  * Containers — `pjit`/`closed_call`/`remat`/`custom_*` recurse at
    cost ×1; `scan` multiplies its body by the trip count; `cond`
    prices the most expensive branch; `while` prices ONE iteration
    (trip count is dynamic — flagged in the report via `dynamic_loops`
    so a reader knows the total is a per-iteration figure there);
    `pallas_call` multiplies its kernel body by the grid size;
    `shard_map` prices the PER-SHARD program (wall-clock view: shards
    run in parallel).

Deliberately dependency-light: operates on jaxpr objects by duck
typing (`.eqns`, `.jaxpr`, avals with `.shape`/`.dtype`), imports
nothing from jax — so it loads anywhere and survives jax-internal
module moves.
"""
from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

# primitives priced as pure data movement (FLOPs 0): layout, slicing,
# indexing, conversion-free reshapes
_DATA_MOVEMENT = frozenset({
    "reshape", "broadcast_in_dim", "squeeze", "transpose", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "gather", "scatter", "rev", "copy", "convert_element_type",
    "bitcast_convert_type", "device_put", "iota", "roll",
    "random_wrap", "random_unwrap", "stop_gradient", "split",
    "program_id", "get", "swap",
})

# reductions: one op per OPERAND element
_REDUCERS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "cumsum", "cumprod", "cummax", "cummin", "reduce_precision",
    "psum", "pmax", "pmin", "all_gather", "reduce_scatter",
})

# container primitives whose cost is their inner jaxpr's, with a
# multiplier; the eqn itself moves no bytes beyond what the body does
_CONTAINERS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "remat", "remat2",
    "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "scan", "while",
    "cond", "shard_map", "pallas_call", "custom_partitioning",
})


class Cost:
    """Mutable accumulator: total flops/bytes + per-primitive rollup."""

    def __init__(self):
        self.flops = 0
        self.hbm_bytes = 0
        self.eqns = 0
        self.dynamic_loops = 0
        self.by_primitive: Dict[str, Dict[str, int]] = {}

    def add(self, prim: str, flops: int, hbm_bytes: int,
            mult: int = 1) -> None:
        flops, hbm_bytes = int(flops) * mult, int(hbm_bytes) * mult
        self.flops += flops
        self.hbm_bytes += hbm_bytes
        self.eqns += 1
        row = self.by_primitive.setdefault(
            prim, {"count": 0, "flops": 0, "hbm_bytes": 0})
        row["count"] += 1
        row["flops"] += flops
        row["hbm_bytes"] += hbm_bytes

    def merge(self, other: "Cost", mult: int = 1) -> None:
        self.flops += other.flops * mult
        self.hbm_bytes += other.hbm_bytes * mult
        self.eqns += other.eqns
        self.dynamic_loops += other.dynamic_loops
        for prim, row in other.by_primitive.items():
            mine = self.by_primitive.setdefault(
                prim, {"count": 0, "flops": 0, "hbm_bytes": 0})
            mine["count"] += row["count"]
            mine["flops"] += row["flops"] * mult
            mine["hbm_bytes"] += row["hbm_bytes"] * mult

    def as_dict(self, top: int = 8) -> dict:
        """Canonical JSON-able report; `by_primitive` keeps the `top`
        most expensive primitives by FLOPs (ties broken by name so the
        report is bit-stable), plus an `other` rollup."""
        rows = sorted(self.by_primitive.items(),
                      key=lambda kv: (-kv[1]["flops"],
                                      -kv[1]["hbm_bytes"], kv[0]))
        head = {k: dict(v) for k, v in rows[:top]}
        tail = rows[top:]
        if tail:
            head["other"] = {
                "count": sum(v["count"] for _, v in tail),
                "flops": sum(v["flops"] for _, v in tail),
                "hbm_bytes": sum(v["hbm_bytes"] for _, v in tail),
            }
        return {
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "eqns": self.eqns,
            "dynamic_loops": self.dynamic_loops,
            "by_primitive": head,
        }


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return _numel(shape) * int(getattr(dtype, "itemsize", 4))


def _numel(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _out_numel(eqn) -> int:
    return sum(_numel(getattr(v.aval, "shape", ()))
               for v in eqn.outvars)


def _operand_avals(eqn):
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "shape", None) is not None:
            yield aval


def _eqn_bytes(eqn) -> int:
    return (sum(aval_bytes(a) for a in _operand_avals(eqn))
            + sum(aval_bytes(v.aval) for v in eqn.outvars))


def _dot_flops(eqn) -> int:
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs, rhs = [a.shape for a in _operand_avals(eqn)][:2]
    k = _numel([lhs[i] for i in lc])
    b = _numel([lhs[i] for i in lb])
    m = _numel([d for i, d in enumerate(lhs)
                if i not in set(lc) | set(lb)])
    n_contract = set(rc)
    n_batch = set(_rb)
    n = _numel([d for i, d in enumerate(rhs)
                if i not in n_contract | n_batch])
    return 2 * b * m * n * k


def _conv_flops(eqn) -> int:
    dn = eqn.params["dimension_numbers"]
    rhs_spec = getattr(dn, "rhs_spec", None)
    avals = list(_operand_avals(eqn))
    rhs = avals[1].shape if len(avals) > 1 else ()
    out = _out_numel(eqn)
    if rhs_spec is None or not rhs:
        return 2 * out
    out_feature_dim = rhs_spec[0]
    k_prod = _numel(rhs) // max(int(rhs[out_feature_dim]), 1)
    groups = int(eqn.params.get("feature_group_count", 1) or 1)
    return 2 * out * (k_prod // max(groups, 1))


def _log2ceil(n: int) -> int:
    return max(1, math.ceil(math.log2(max(int(n), 2))))


def sort_width(eqn) -> int:
    """Length of the dimension a `sort` eqn actually sorts along —
    the cost driver. `jnp.median(table, axis=0)` sorts a [5, 500000]
    operand along dimension 0: half a million independent 5-wide
    sorts, nothing like a 500000-wide sorting network; pricing (or
    flagging, audit AU003) by the trailing dim would be wrong by 5e5."""
    shapes = [a.shape for a in _operand_avals(eqn) if a.shape]
    if not shapes:
        return 2
    dim = eqn.params.get("dimension")
    if dim is None:
        dim = len(shapes[0]) - 1
    return int(shapes[0][dim])


def sub_jaxprs(value) -> Iterable:
    """Jaxpr-like objects inside one eqn param value (ClosedJaxpr has
    `.jaxpr.eqns`, raw Jaxpr has `.eqns`), by duck typing."""
    vals = value if isinstance(value, (list, tuple)) else [value]
    for v in vals:
        inner = getattr(v, "jaxpr", None)
        if inner is not None and hasattr(inner, "eqns"):
            yield inner
        elif hasattr(v, "eqns"):
            yield v


def _container_multiplier(eqn) -> int:
    name = eqn.primitive.name
    if name == "scan":
        return max(int(eqn.params.get("length", 1) or 1), 1)
    if name == "pallas_call":
        gm = eqn.params.get("grid_mapping")
        grid = getattr(gm, "grid", None) if gm is not None else None
        if grid is None:
            grid = eqn.params.get("grid", ())
        try:
            return max(_numel(grid), 1)
        except (TypeError, ValueError):
            return 1
    return 1


def jaxpr_cost(jaxpr) -> Cost:
    """Price one jaxpr (Closed or raw), recursively."""
    inner = getattr(jaxpr, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        jaxpr = inner
    cost = Cost()
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _CONTAINERS or any(
                True for v in eqn.params.values()
                for _ in sub_jaxprs(v)):
            mult = _container_multiplier(eqn)
            if name == "while":
                cost.dynamic_loops += 1
            subs = [s for v in eqn.params.values()
                    for s in sub_jaxprs(v)]
            if name == "cond":
                # price the most expensive branch (the dispatched
                # round takes one; max is the conservative choice)
                branch_costs = [jaxpr_cost(s) for s in subs]
                if branch_costs:
                    cost.merge(max(branch_costs,
                                   key=lambda c: (c.flops,
                                                  c.hbm_bytes)), mult)
            else:
                for s in subs:
                    cost.merge(jaxpr_cost(s), mult)
            continue
        if name in ("dot_general",):
            cost.add(name, _dot_flops(eqn), _eqn_bytes(eqn))
        elif name == "conv_general_dilated":
            cost.add(name, _conv_flops(eqn), _eqn_bytes(eqn))
        elif name == "sort":
            n = max((_numel(a.shape) for a in _operand_avals(eqn)),
                    default=0)
            cost.add(name, n * _log2ceil(sort_width(eqn)),
                     _eqn_bytes(eqn))
        elif name in ("top_k", "approx_top_k"):
            n = max((_numel(a.shape) for a in _operand_avals(eqn)),
                    default=0)
            k = int(eqn.params.get("k",
                                   eqn.params.get("reduction_input_size_override",
                                                  2)) or 2)
            cost.add(name, n * _log2ceil(abs(k)), _eqn_bytes(eqn))
        elif name in _REDUCERS:
            n = sum(_numel(a.shape) for a in _operand_avals(eqn))
            cost.add(name, n, _eqn_bytes(eqn))
        elif name in _DATA_MOVEMENT:
            cost.add(name, 0, _eqn_bytes(eqn))
        else:
            # elementwise default: one op per output element
            cost.add(name, _out_numel(eqn), _eqn_bytes(eqn))
    return cost

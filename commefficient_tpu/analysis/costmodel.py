"""Static per-primitive cost model over jaxprs: FLOPs + HBM bytes.

The hardware-independent half of the PERF story (ISSUE 7): PR 6's
kernel/perf claims are TPU-pending because the tunnel is down, but the
PROGRAM is fully known at trace time — so this module walks a
ClosedJaxpr and prices every equation with a deterministic analytic
model. The absolute numbers are coarse (see the honesty notes below);
what the auditor gates on is their STABILITY: the same config must
price to the identical integer on every trace, so any drift in the
committed `audit.baseline.json` is a real program change someone must
look at — the static stand-in for a bench regression gate.

Model (deliberately simple, deliberately documented):

  * FLOPs — `dot_general` and `conv_general_dilated` get the exact
    2·M·N·K count from their dimension numbers; `sort`/`top_k` are
    priced as comparison networks (n·ceil(log2 n), n·ceil(log2 k));
    reductions cost their operand size; everything else costs its
    output size (one op per output element — transcendentals are
    undercounted by a small constant factor, uniformly, which cancels
    in a regression diff).
  * HBM bytes — every equation is priced as if un-fused: operand bytes
    in + result bytes out. Real XLA fuses elementwise chains, so this
    is an UPPER BOUND on traffic, not a prediction — but a new
    intermediate buffer shows up in it immediately, which is the
    regression class (an accidental [D]-materialization) the gate
    exists to catch.
  * Containers — `pjit`/`closed_call`/`remat`/`custom_*` recurse at
    cost ×1; `scan` multiplies its body by the trip count; `cond`
    prices the most expensive branch; `while` prices ONE iteration
    (trip count is dynamic — flagged in the report via `dynamic_loops`
    so a reader knows the total is a per-iteration figure there);
    `pallas_call` multiplies its kernel body by the grid size;
    `shard_map` prices the PER-SHARD program (wall-clock view: shards
    run in parallel).

Deliberately dependency-light: operates on jaxpr objects by duck
typing (`.eqns`, `.jaxpr`, avals with `.shape`/`.dtype`), imports
nothing from jax — so it loads anywhere and survives jax-internal
module moves.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Iterable, List, Optional, Tuple

# primitives priced as pure data movement (FLOPs 0): layout, slicing,
# indexing, conversion-free reshapes
_DATA_MOVEMENT = frozenset({
    "reshape", "broadcast_in_dim", "squeeze", "transpose", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "gather", "scatter", "rev", "copy", "convert_element_type",
    "bitcast_convert_type", "device_put", "iota", "roll",
    "random_wrap", "random_unwrap", "stop_gradient", "split",
    "program_id", "get", "swap",
})

# reductions: one op per OPERAND element
_REDUCERS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "cumsum", "cumprod", "cummax", "cummin", "reduce_precision",
    "psum", "pmax", "pmin", "all_gather", "reduce_scatter",
})

# container primitives whose cost is their inner jaxpr's, with a
# multiplier; the eqn itself moves no bytes beyond what the body does
_CONTAINERS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "remat", "remat2",
    "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "scan", "while",
    "cond", "shard_map", "pallas_call", "custom_partitioning",
})


class Cost:
    """Mutable accumulator: total flops/bytes + per-primitive rollup."""

    def __init__(self):
        self.flops = 0
        self.hbm_bytes = 0
        self.eqns = 0
        self.dynamic_loops = 0
        self.by_primitive: Dict[str, Dict[str, int]] = {}

    def add(self, prim: str, flops: int, hbm_bytes: int,
            mult: int = 1) -> None:
        flops, hbm_bytes = int(flops) * mult, int(hbm_bytes) * mult
        self.flops += flops
        self.hbm_bytes += hbm_bytes
        self.eqns += 1
        row = self.by_primitive.setdefault(
            prim, {"count": 0, "flops": 0, "hbm_bytes": 0})
        row["count"] += 1
        row["flops"] += flops
        row["hbm_bytes"] += hbm_bytes

    def merge(self, other: "Cost", mult: int = 1) -> None:
        self.flops += other.flops * mult
        self.hbm_bytes += other.hbm_bytes * mult
        self.eqns += other.eqns
        self.dynamic_loops += other.dynamic_loops
        for prim, row in other.by_primitive.items():
            mine = self.by_primitive.setdefault(
                prim, {"count": 0, "flops": 0, "hbm_bytes": 0})
            mine["count"] += row["count"]
            mine["flops"] += row["flops"] * mult
            mine["hbm_bytes"] += row["hbm_bytes"] * mult

    def as_dict(self, top: int = 8) -> dict:
        """Canonical JSON-able report; `by_primitive` keeps the `top`
        most expensive primitives by FLOPs (ties broken by name so the
        report is bit-stable), plus an `other` rollup."""
        rows = sorted(self.by_primitive.items(),
                      key=lambda kv: (-kv[1]["flops"],
                                      -kv[1]["hbm_bytes"], kv[0]))
        head = {k: dict(v) for k, v in rows[:top]}
        tail = rows[top:]
        if tail:
            head["other"] = {
                "count": sum(v["count"] for _, v in tail),
                "flops": sum(v["flops"] for _, v in tail),
                "hbm_bytes": sum(v["hbm_bytes"] for _, v in tail),
            }
        return {
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "eqns": self.eqns,
            "dynamic_loops": self.dynamic_loops,
            "by_primitive": head,
        }


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return _numel(shape) * int(getattr(dtype, "itemsize", 4))


def _numel(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _out_numel(eqn) -> int:
    return sum(_numel(getattr(v.aval, "shape", ()))
               for v in eqn.outvars)


def _operand_avals(eqn):
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "shape", None) is not None:
            yield aval


def _eqn_bytes(eqn) -> int:
    return (sum(aval_bytes(a) for a in _operand_avals(eqn))
            + sum(aval_bytes(v.aval) for v in eqn.outvars))


def _dot_flops(eqn) -> int:
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs, rhs = [a.shape for a in _operand_avals(eqn)][:2]
    k = _numel([lhs[i] for i in lc])
    b = _numel([lhs[i] for i in lb])
    m = _numel([d for i, d in enumerate(lhs)
                if i not in set(lc) | set(lb)])
    n_contract = set(rc)
    n_batch = set(_rb)
    n = _numel([d for i, d in enumerate(rhs)
                if i not in n_contract | n_batch])
    return 2 * b * m * n * k


def _conv_flops(eqn) -> int:
    dn = eqn.params["dimension_numbers"]
    rhs_spec = getattr(dn, "rhs_spec", None)
    avals = list(_operand_avals(eqn))
    rhs = avals[1].shape if len(avals) > 1 else ()
    out = _out_numel(eqn)
    if rhs_spec is None or not rhs:
        return 2 * out
    out_feature_dim = rhs_spec[0]
    k_prod = _numel(rhs) // max(int(rhs[out_feature_dim]), 1)
    groups = int(eqn.params.get("feature_group_count", 1) or 1)
    return 2 * out * (k_prod // max(groups, 1))


def _log2ceil(n: int) -> int:
    return max(1, math.ceil(math.log2(max(int(n), 2))))


def sort_width(eqn) -> int:
    """Length of the dimension a `sort` eqn actually sorts along —
    the cost driver. `jnp.median(table, axis=0)` sorts a [5, 500000]
    operand along dimension 0: half a million independent 5-wide
    sorts, nothing like a 500000-wide sorting network; pricing (or
    flagging, audit AU003) by the trailing dim would be wrong by 5e5."""
    shapes = [a.shape for a in _operand_avals(eqn) if a.shape]
    if not shapes:
        return 2
    dim = eqn.params.get("dimension")
    if dim is None:
        dim = len(shapes[0]) - 1
    return int(shapes[0][dim])


def sub_jaxprs(value) -> Iterable:
    """Jaxpr-like objects inside one eqn param value (ClosedJaxpr has
    `.jaxpr.eqns`, raw Jaxpr has `.eqns`), by duck typing."""
    vals = value if isinstance(value, (list, tuple)) else [value]
    for v in vals:
        inner = getattr(v, "jaxpr", None)
        if inner is not None and hasattr(inner, "eqns"):
            yield inner
        elif hasattr(v, "eqns"):
            yield v


def _container_multiplier(eqn) -> int:
    name = eqn.primitive.name
    if name == "scan":
        return max(int(eqn.params.get("length", 1) or 1), 1)
    if name == "pallas_call":
        gm = eqn.params.get("grid_mapping")
        grid = getattr(gm, "grid", None) if gm is not None else None
        if grid is None:
            grid = eqn.params.get("grid", ())
        try:
            return max(_numel(grid), 1)
        except (TypeError, ValueError):
            return 1
    return 1


def jaxpr_cost(jaxpr) -> Cost:
    """Price one jaxpr (Closed or raw), recursively."""
    inner = getattr(jaxpr, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        jaxpr = inner
    cost = Cost()
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _CONTAINERS or any(
                True for v in eqn.params.values()
                for _ in sub_jaxprs(v)):
            mult = _container_multiplier(eqn)
            if name == "while":
                cost.dynamic_loops += 1
            subs = [s for v in eqn.params.values()
                    for s in sub_jaxprs(v)]
            if name == "cond":
                # price the most expensive branch (the dispatched
                # round takes one; max is the conservative choice)
                branch_costs = [jaxpr_cost(s) for s in subs]
                if branch_costs:
                    cost.merge(max(branch_costs,
                                   key=lambda c: (c.flops,
                                                  c.hbm_bytes)), mult)
            else:
                for s in subs:
                    cost.merge(jaxpr_cost(s), mult)
            continue
        if name in ("dot_general",):
            cost.add(name, _dot_flops(eqn), _eqn_bytes(eqn))
        elif name == "conv_general_dilated":
            cost.add(name, _conv_flops(eqn), _eqn_bytes(eqn))
        elif name == "sort":
            n = max((_numel(a.shape) for a in _operand_avals(eqn)),
                    default=0)
            cost.add(name, n * _log2ceil(sort_width(eqn)),
                     _eqn_bytes(eqn))
        elif name in ("top_k", "approx_top_k"):
            n = max((_numel(a.shape) for a in _operand_avals(eqn)),
                    default=0)
            k = int(eqn.params.get("k",
                                   eqn.params.get("reduction_input_size_override",
                                                  2)) or 2)
            cost.add(name, n * _log2ceil(abs(k)), _eqn_bytes(eqn))
        elif name in _REDUCERS:
            n = sum(_numel(a.shape) for a in _operand_avals(eqn))
            cost.add(name, n, _eqn_bytes(eqn))
        elif name in _DATA_MOVEMENT:
            cost.add(name, 0, _eqn_bytes(eqn))
        else:
            # elementwise default: one op per output element
            cost.add(name, _out_numel(eqn), _eqn_bytes(eqn))
    return cost


# ---------------------------------------------------------------------------
# per-link collective cost (graftmesh, ISSUE 8)
#
# The FLOPs/HBM model above prices a program as if it ran on one
# device; the collective model below prices its COMMUNICATION under an
# explicit mesh, split by link class — intra-slice ICI vs inter-slice
# DCN — because the round engine's scaling contract is stated in
# exactly those terms (parallel/mesh.make_multihost_client_mesh: one
# table-sized all-reduce crosses DCN per round, model-axis collectives
# never do). Like the FLOPs model it is a MODEL, not a prediction:
# every collective is priced as a hierarchical ring (one ring stage
# per slice over ICI, one ring over the slices for the DCN stage),
# all-reduce at factor 2 (reduce-scatter + all-gather), everything
# else at factor 1. The absolute bytes are approximate; what the
# meshaudit baseline gates on is their STABILITY and their SPLIT —
# a new collective, a payload that grew, or traffic moving from ICI
# to DCN all change the report exactly.

# collective primitive names -> byte factor over the payload; the
# payload is operand bytes (reduce-type) or output bytes (all_gather,
# whose logical payload is the gathered result)
_COLLECTIVE_FACTORS = {
    "psum": 2, "psum2": 2, "psum_invariant": 2, "pmax": 2, "pmin": 2,
    "all_gather": 1, "reduce_scatter": 1, "all_to_all": 1,
    "ppermute": 1, "pbroadcast": 1,
}
_OUTPUT_PAYLOAD = frozenset({"all_gather"})


@dataclasses.dataclass(frozen=True)
class MeshLinkModel:
    """Link-class description of one mesh, consumed by
    `collective_cost`. Deliberately jax-free: the shardaudit tier
    builds one from a real jax Mesh + slice map; tests can construct
    them directly.

    axis_sizes:  {axis name: device count along it}
    axis_slices: {axis name: number of DISTINCT slices one group along
                  that axis spans}. 1 means the axis is pure ICI; S > 1
                  means a collective over it must run a DCN stage over
                  S slice groups (with size/S devices per slice on ICI).
    """
    name: str
    axis_sizes: Tuple[Tuple[str, int], ...]
    axis_slices: Tuple[Tuple[str, int], ...]

    def size(self, axis: str) -> int:
        return dict(self.axis_sizes).get(axis, 1)

    def slices(self, axis: str) -> int:
        return dict(self.axis_slices).get(axis, 1)

    def as_dict(self) -> dict:
        return {"axes": {a: n for a, n in self.axis_sizes},
                "slices": {a: s for a, s in self.axis_slices}}


@dataclasses.dataclass
class CollectiveRecord:
    """One collective equation, priced. `mult` is the container
    multiplier (a collective inside a scanned span of N rounds runs N
    times; bytes below already include it)."""
    kind: str
    axes: Tuple[str, ...]
    payload_bytes: int               # one execution's logical payload
    operand_shapes: Tuple[Tuple[int, ...], ...]
    mult: int
    ici_bytes: int                   # mult-inclusive
    dcn_bytes: int                   # mult-inclusive
    crosses_dcn: bool


class CollectiveCost:
    """Per-link rollup of every collective in one program."""

    def __init__(self):
        self.records: List[CollectiveRecord] = []
        self.ici_bytes = 0
        self.dcn_bytes = 0
        self.dcn_collectives = 0     # mult-inclusive executions

    def add(self, rec: CollectiveRecord) -> None:
        self.records.append(rec)
        self.ici_bytes += rec.ici_bytes
        self.dcn_bytes += rec.dcn_bytes
        if rec.crosses_dcn:
            self.dcn_collectives += rec.mult

    def as_dict(self) -> dict:
        """Canonical JSON-able per-link report (bit-stable ordering)."""
        by_kind: Dict[str, Dict[str, int]] = {}
        for r in self.records:
            row = by_kind.setdefault(r.kind, {"count": 0, "bytes": 0})
            row["count"] += r.mult
            row["bytes"] += r.ici_bytes + r.dcn_bytes
        return {
            "ici_bytes": self.ici_bytes,
            "dcn_bytes": self.dcn_bytes,
            "dcn_collectives": self.dcn_collectives,
            "collectives": {k: dict(by_kind[k]) for k in sorted(by_kind)},
        }


def eqn_collective_axes(eqn) -> Tuple[str, ...]:
    """Named mesh axes one collective eqn spans (positional axis
    indices — vmapped collectives — carry no mesh link and are
    skipped)."""
    axes = eqn.params.get("axes")
    if axes is None:
        axes = eqn.params.get("axis_name", ())
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _price_collective(eqn, link: MeshLinkModel, mult: int
                      ) -> Optional[CollectiveRecord]:
    kind = eqn.primitive.name
    factor = _COLLECTIVE_FACTORS[kind]
    axes = eqn_collective_axes(eqn)
    if not axes:
        return None
    if kind in _OUTPUT_PAYLOAD:
        payload = sum(aval_bytes(v.aval) for v in eqn.outvars)
    else:
        payload = sum(aval_bytes(a) for a in _operand_avals(eqn))
    ici = dcn = 0
    crosses = False
    # hierarchical ring, axis by axis: S slice groups of n/S devices —
    # each slice group rings the payload over ICI, then one ring over
    # the S groups crosses DCN with the full payload
    for a in axes:
        n = link.size(a)
        s = max(link.slices(a), 1)
        n_inner = max(n // s, 1)
        ici += factor * (n_inner - 1) * payload * s
        if s > 1:
            dcn += factor * (s - 1) * payload
            crosses = True
    return CollectiveRecord(
        kind=kind, axes=axes, payload_bytes=payload,
        operand_shapes=tuple(tuple(int(d) for d in a.shape)
                             for a in _operand_avals(eqn)),
        mult=mult, ici_bytes=ici * mult, dcn_bytes=dcn * mult,
        crosses_dcn=crosses)


def collective_cost(jaxpr, link: MeshLinkModel) -> CollectiveCost:
    """Walk one jaxpr (Closed or raw) and price every collective over
    `link`'s axes, carrying container multipliers (scan trip counts)
    exactly like `jaxpr_cost`."""
    inner = getattr(jaxpr, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        jaxpr = inner
    cost = CollectiveCost()

    def walk(jx, mult):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in _COLLECTIVE_FACTORS:
                rec = _price_collective(eqn, link, mult)
                if rec is not None:
                    cost.add(rec)
            sub_mult = mult * _container_multiplier(eqn)
            for v in eqn.params.values():
                for s in sub_jaxprs(v):
                    walk(s, sub_mult)

    walk(jaxpr, 1)
    return cost


# ---------------------------------------------------------------------------
# reassociation ulp bound (graftnum, ISSUE 18)
#
# Floating-point addition is not associative: summing the same n shard
# contributions in two different association orders can differ by up to
# (n - 1) rounding steps — the textbook worst-case forward bound for
# recursive summation, |err| <= (n - 1) * eps * sum|x| (Higham, ch. 4),
# i.e. (n - 1) result-ulps per element. Within one compiled program XLA
# fixes the reduction order, so single-device replay is bit-exact; the
# order that is NOT fixed by any spec is the cross-shard combine of a
# psum-class collective (topology, ring direction, and slice layout all
# legally reassociate it). graftnum therefore PRICES that exposure
# instead of flagging it: per program, the sum over sum-type
# collectives of container-multiplier x (participants - 1), an integer
# that moves exactly when a program adds a collective, widens an axis,
# or scans more rounds per dispatch — and is diffed exact-match in
# graftnum.baseline.json like FLOPs/HBM are in audit.baseline.json.

# sum-type collectives only: pmax/pmin are exact order-free selections
# and the data-movement collectives (all_gather, ppermute, all_to_all,
# pbroadcast) round nothing
_REASSOC_COLLECTIVES = frozenset({
    "psum", "psum2", "psum_invariant", "reduce_scatter",
})


def _reduces_floats(eqn) -> bool:
    return any(str(getattr(a, "dtype", "")).startswith(("float",
                                                        "bfloat"))
               for a in _operand_avals(eqn))


def reassociation_ulp_bound(jaxpr, axis_sizes: Dict[str, int],
                            default_axis_size: int = 2) -> int:
    """Worst-case per-element ulp divergence between two legal
    reassociations of `jaxpr`'s cross-shard sum reductions.

    `axis_sizes` maps named mesh axes to participant counts (an axis
    the caller did not declare prices at `default_axis_size` — the
    smallest exposure a real multi-participant axis can have, so an
    unregistered axis is never silently free). Integer psums are exact
    and price 0. Deterministic given the jaxpr, like jaxpr_cost."""
    inner = getattr(jaxpr, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        jaxpr = inner
    total = 0

    def walk(jx, mult):
        nonlocal total
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in _REASSOC_COLLECTIVES and _reduces_floats(eqn):
                n = 1
                for a in eqn_collective_axes(eqn):
                    n *= max(int(axis_sizes.get(a, default_axis_size)),
                             1)
                if n > 1:
                    total += mult * (n - 1)
            sub_mult = mult * _container_multiplier(eqn)
            for v in eqn.params.values():
                for s in sub_jaxprs(v):
                    walk(s, sub_mult)

    walk(jaxpr, 1)
    return int(total)

"""Rényi (moments-accountant) privacy tracking for dp_sketch.

Each dp_sketch round is one Gaussian mechanism release with noise
multiplier sigma = dp_noise_mult: the aggregated table has per-client
l2 sensitivity dp_clip and noise std dp_noise_mult * dp_clip, so in
normalized units the mechanism is N(0, sigma^2) on a sensitivity-1
query. Its Rényi divergence at order alpha is the classic

    RDP(alpha) = alpha / (2 * sigma^2)

(Mironov 2017, Prop. 7). RDP composes ADDITIVELY over rounds, and the
standard conversion (Mironov 2017, Prop. 3) turns the composed RDP
curve into (epsilon, delta)-DP:

    epsilon(T) = min_alpha [ T * alpha / (2 sigma^2)
                             + log(1/delta) / (alpha - 1) ]

The minimization over a fixed finite alpha grid makes epsilon a pure,
deterministic function of (sigma, delta, T) — the host recomputes it
from the rounds-done count, so crash->resume re-derives the identical
budget trajectory with no accountant state in the checkpoint.

``closed_form_epsilon`` is the exact continuous-alpha minimum
(alpha* = 1 + sigma * sqrt(2 log(1/delta) / T)), used by the tests as
an independent reference the grid answer must hug from above.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence


def default_alphas() -> tuple:
    """The standard accountant grid: dense steps near 1 (where the
    optimum lands for small T / large sigma) plus integer orders out
    to 64 for the high-composition regime."""
    fine = tuple(1.0 + x / 10.0 for x in range(1, 100))
    coarse = tuple(float(a) for a in range(11, 65))
    return fine + coarse


def closed_form_epsilon(sigma: float, delta: float, steps: int) -> float:
    """Exact continuous-alpha minimum of the composed Gaussian RDP
    conversion: epsilon* = T / (2 sigma^2) + sqrt(2 T log(1/delta)) / sigma.
    """
    if steps <= 0:
        return 0.0
    t = float(steps)
    return t / (2.0 * sigma * sigma) + math.sqrt(
        2.0 * t * math.log(1.0 / delta)) / sigma


class RdpAccountant:
    """Tracks cumulative (epsilon, delta) for T composed Gaussian
    mechanism rounds at noise multiplier ``noise_multiplier``.

    Stateless by design: ``epsilon(steps)`` is a pure function of the
    step count, so the host journals it per round and resume simply
    recomputes from the restored round counter.
    """

    def __init__(self, noise_multiplier: float, delta: float,
                 alphas: Optional[Sequence[float]] = None):
        if noise_multiplier <= 0:
            raise ValueError(
                f"noise_multiplier={noise_multiplier} must be > 0")
        if not 0.0 < delta < 1.0:
            raise ValueError(f"delta={delta} must be in (0, 1)")
        self.noise_multiplier = float(noise_multiplier)
        self.delta = float(delta)
        self.alphas = tuple(float(a) for a in
                            (alphas if alphas is not None
                             else default_alphas()))
        if any(a <= 1.0 for a in self.alphas):
            raise ValueError("all RDP orders must be > 1")

    def rdp(self, steps: int, alpha: float) -> float:
        """Composed Rényi divergence at order alpha after ``steps``
        rounds."""
        s = self.noise_multiplier
        return steps * alpha / (2.0 * s * s)

    def epsilon(self, steps: int) -> float:
        """Cumulative (epsilon, self.delta)-DP guarantee after
        ``steps`` rounds — min over the alpha grid of the RDP->DP
        conversion."""
        if steps <= 0:
            return 0.0
        log_inv_delta = math.log(1.0 / self.delta)
        return min(self.rdp(steps, a) + log_inv_delta / (a - 1.0)
                   for a in self.alphas)

"""dp_sketch: differentially-private FetchSGD transport (ISSUE 19
plugin #2, the FedSKETCH-style DP scenario from PAPERS.md).

The Gaussian mechanism applied in SKETCH SPACE:

  * every client encodes its gradient into the [r, c] count-sketch
    table PER CLIENT (never the deferred shard-sum encode — the clip
    below is nonlinear) and, after the count scaling that makes its
    table the client's SUM contribution, clips the table's Frobenius
    norm to --dp_clip. Each client's contribution to the psum'd
    aggregate is therefore bounded by dp_clip, i.e. the sum query's
    l2 sensitivity to one client is exactly dp_clip;
  * ONCE per round, calibrated Gaussian noise with
    std = dp_noise_mult * dp_clip is added to the aggregated table
    inside the jitted round, on the registered "dp" PRNG domain
    folded into the round key (deterministic in (seed, round):
    crash->resume replays the identical noise, and GL009 keeps the
    domain honest);
  * everything downstream — divide-by-total, server-side virtual
    momentum/error, top-k decode — is post-processing, which costs no
    additional privacy.

Composition over rounds is tracked by the Rényi accountant
(compress/privacy.py): the host journals a `privacy` event with the
cumulative epsilon each round and fails LOUD when --dp_target_epsilon
is exhausted.

Deliberately rejected compositions (validate below): --dp (the PR-0
per-gradient worker/server DP path — two mechanisms would double-
count the budget) and the robust aggregators (an order statistic is
not the bounded-sensitivity SUM the noise is calibrated for). The
admission screen and byzantine drills compose fine: screening only
REMOVES clients, and a sum over fewer dp_clip-bounded contributions
keeps its sensitivity bound.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from commefficient_tpu.compress.base import Compressor
from commefficient_tpu.ops.flat import clip_to_l2
from commefficient_tpu.ops.sketch import CSVec


class DpSketchCompressor(Compressor):
    name = "dp_sketch"
    sketch_like = True

    # ---- static specs -------------------------------------------------
    def wire_floats(self, cfg) -> int:
        return cfg.num_rows * cfg.num_cols

    # wire_bytes: base 4 * wire_floats — the dp_sketch table rides the
    # wire at f32 (sketch_table_dtype is validated sketch-only; a
    # quantized DP table would need its own sensitivity analysis)

    def validate(self, cfg) -> None:
        if cfg.dp_noise_mult <= 0:
            raise ValueError(
                "dp_sketch requires --dp_noise_mult > 0: zero noise "
                "is not differential privacy — use --mode sketch for "
                "the noise-free transport (compress/dp_sketch.py)")
        if cfg.dp_clip <= 0:
            raise ValueError(
                f"dp_clip={cfg.dp_clip} must be > 0 (the per-client "
                "sketch-table sensitivity bound)")
        if not 0.0 < cfg.dp_delta < 1.0:
            raise ValueError(
                f"dp_delta={cfg.dp_delta} must be in (0, 1)")
        if cfg.dp_target_epsilon < 0:
            raise ValueError(
                f"dp_target_epsilon={cfg.dp_target_epsilon} must be "
                ">= 0 (0 = track epsilon but never fail)")
        if cfg.error_type == "local":
            raise ValueError(
                "dp_sketch cannot use per-client local error "
                "accumulation (same table-space contract as sketch "
                "mode)")
        if cfg.local_momentum != 0:
            raise ValueError(
                "dp_sketch cannot use local momentum (same table-"
                "space contract as sketch mode)")
        if cfg.do_dp:
            raise ValueError(
                "--dp (the per-gradient worker/server DP path) and "
                "--mode dp_sketch are mutually exclusive: two "
                "mechanisms would each consume privacy budget the "
                "accountant tracks only once (compress/dp_sketch.py)")
        if cfg.robust_aggregation:
            raise ValueError(
                "dp_sketch does not compose with robust aggregators "
                f"(--aggregator {cfg.aggregator}): the Gaussian noise "
                "is calibrated for the bounded-sensitivity SUM of "
                "dp_clip-clipped tables, and an order statistic has "
                "no such sensitivity bound — pick one "
                "(compress/dp_sketch.py)")

    # ---- traced hooks -------------------------------------------------
    def encode(self, cfg, grad, key=None):
        # always per-client (never the deferred shard-sum encode):
        # the sensitivity clip in residual() is nonlinear
        sketch = CSVec(d=cfg.grad_size, c=cfg.num_cols,
                       r=cfg.num_rows, num_blocks=cfg.num_blocks,
                       seed=42, backend=cfg.kernel_backend)
        return sketch.encode(grad)

    def residual(self, cfg, to_transmit, error, velocity, key=None):
        # to_transmit is the count-scaled [r, c] table — this client's
        # additive contribution to the round's sum. Frobenius-clip it
        # to dp_clip: the sum query's per-client l2 sensitivity bound
        # the noise is calibrated against.
        return clip_to_l2(to_transmit, cfg.dp_clip), error, velocity

    def post_aggregate(self, cfg, transmit, round_key):
        from commefficient_tpu.analysis.domains import domain
        noise_key = jax.random.fold_in(round_key, domain("dp"))
        sigma = cfg.dp_noise_mult * cfg.dp_clip
        return transmit + sigma * jax.random.normal(
            noise_key, transmit.shape, jnp.float32)

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        # post-processing: the noisy aggregate table rides the exact
        # sketch-mode server path (virtual momentum/error in table
        # space, top-k decode)
        from commefficient_tpu.federated import server as fserver
        return fserver._sketched(gradient, Vvelocity, Verror, cfg,
                                 lr, key)

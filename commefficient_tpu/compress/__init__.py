"""compress/: the Compressor plugin registry (ISSUE 19).

One plugin per ``Config.mode`` value. The engine resolves its plugin
ONCE per traced-program family (``get_compressor(cfg.mode)``) and
routes every mode-specific decision — wire geometry, client-state
blocks, the four traced round seams, config invariants — through it.

Import-order contract: this package may import ``config`` (for the
MODES coverage assert below), and config's spec properties import
THIS package lazily at property-call time — config never imports
compress at module level, so there is no cycle. The plugin modules
import ``federated.*`` lazily inside their ``decode`` hooks for the
same reason (federated/__init__ pulls the whole engine, which imports
config, which must already be importable).
"""
from __future__ import annotations

from typing import Dict

from commefficient_tpu.compress.base import Compressor
from commefficient_tpu.compress.dp_sketch import DpSketchCompressor
from commefficient_tpu.compress.modes import (FedavgCompressor,
                                              LocalTopkCompressor,
                                              SketchCompressor,
                                              TrueTopkCompressor,
                                              UncompressedCompressor)
from commefficient_tpu.compress.powersgd import PowerSGDCompressor
from commefficient_tpu.compress.privacy import (RdpAccountant,
                                                closed_form_epsilon)

_REGISTRY: Dict[str, Compressor] = {}


def register(comp: Compressor) -> Compressor:
    """Register a plugin under ``comp.name``. Re-registering a name is
    an error — plugins are process-global singletons."""
    if not comp.name:
        raise ValueError(f"{type(comp).__name__} has an empty name")
    if comp.name in _REGISTRY:
        raise ValueError(
            f"compressor {comp.name!r} is already registered "
            f"({type(_REGISTRY[comp.name]).__name__})")
    _REGISTRY[comp.name] = comp
    return comp


def get_compressor(mode: str) -> Compressor:
    """The plugin for a Config.mode value, raising loudly on unknown
    names."""
    try:
        return _REGISTRY[mode]
    except KeyError:
        raise KeyError(
            f"no compressor registered for mode {mode!r}; "
            f"registered: {sorted(_REGISTRY)}") from None


def registered_modes() -> tuple:
    return tuple(sorted(_REGISTRY))


for _comp in (SketchCompressor(), TrueTopkCompressor(),
              LocalTopkCompressor(), FedavgCompressor(),
              UncompressedCompressor(), PowerSGDCompressor(),
              DpSketchCompressor()):
    register(_comp)
del _comp


def _assert_covers_modes() -> None:
    # every Config.mode has a plugin and every plugin is a mode —
    # drift in either direction is a packaging bug, not a user error
    from commefficient_tpu.config import MODES
    if set(_REGISTRY) != set(MODES):
        raise AssertionError(
            f"compressor registry {sorted(_REGISTRY)} != config.MODES "
            f"{sorted(MODES)}")


_assert_covers_modes()

__all__ = [
    "Compressor", "RdpAccountant", "closed_form_epsilon",
    "get_compressor", "register", "registered_modes",
]

"""The five classic modes as Compressor plugins (ISSUE 19 migration).

Bit-identity contract: every traced hook here either is the base-class
identity or contains the EXACT code the engine ran inline before the
migration (forward_grad's sketch encode, local_step's local_topk
sparsify-and-mask) or delegates to the untouched server helpers
(federated/server._sketched/_true_topk/_local_topk/_fedavg/
_uncompressed). Dispatch moved from ``cfg.mode == ...`` branches to
the registry, but dispatch is static config — the traced round
programs are byte-identical, which graftaudit/graftnum's exact-match
baselines prove on every run.

The server helpers are imported lazily inside ``decode``:
federated/server imports config at module load, and config's spec
properties import this package, so a module-level import here would
cycle.
"""
from __future__ import annotations

from commefficient_tpu.ops.flat import clip_table_to_l2, masked_topk
from commefficient_tpu.ops.sketch import CSVec


from commefficient_tpu.compress.base import Compressor


def _fserver():
    from commefficient_tpu.federated import server as fserver
    return fserver


class SketchCompressor(Compressor):
    """FetchSGD count-sketch transport (the reference's headline
    mode): per-client [r, c] tables, linear aggregation, server-side
    top-k decode with virtual momentum/error in table space."""
    name = "sketch"
    sketch_like = True

    def wire_floats(self, cfg) -> int:
        return cfg.num_rows * cfg.num_cols

    def wire_bytes(self, cfg) -> int:
        # quantized wire transport (--sketch_table_dtype): bill at the
        # realized element size, plus int8's per-row f32 scales
        from commefficient_tpu.ops.kernels.quant import wire_table_bytes
        return wire_table_bytes(cfg.num_rows, cfg.num_cols,
                                cfg.sketch_table_dtype)

    def encode(self, cfg, grad, key=None):
        if cfg.defer_sketch_encode:
            # linearity: the round engine encodes the per-shard client
            # SUM once, instead of one table per client (Config
            # property docstring; round.py shard_train)
            return grad
        sketch = CSVec(d=cfg.grad_size, c=cfg.num_cols,
                       r=cfg.num_rows, num_blocks=cfg.num_blocks,
                       seed=42, backend=cfg.kernel_backend)
        table = sketch.encode(grad)
        if cfg.max_grad_norm is not None:
            table = clip_table_to_l2(
                table, sketch.l2estimate(table), cfg.max_grad_norm)
        return table

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        return _fserver()._sketched(gradient, Vvelocity, Verror, cfg,
                                    lr, key)


class TrueTopkCompressor(Compressor):
    """Exact top-k of the summed dense gradient, selected at the
    server with virtual momentum/error feedback."""
    name = "true_topk"

    def wire_floats(self, cfg) -> int:
        return cfg.grad_size

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        return _fserver()._true_topk(gradient, Vvelocity, Verror, cfg,
                                     lr, key)


class LocalTopkCompressor(Compressor):
    """Per-client top-k sparsification with local error feedback and
    momentum factor masking."""
    name = "local_topk"

    def wire_floats(self, cfg) -> int:
        return cfg.k

    def residual(self, cfg, to_transmit, error, velocity, key=None):
        to_transmit = masked_topk(to_transmit, k=cfg.k)
        not_sent = (to_transmit == 0).astype(to_transmit.dtype)
        if cfg.error_type == "local":
            error = error * not_sent           # error feedback
        if cfg.local_momentum > 0:
            velocity = velocity * not_sent     # momentum factor masking
        return to_transmit, error, velocity

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        return _fserver()._local_topk(gradient, Vvelocity, Verror, cfg,
                                      lr, key)


class FedavgCompressor(Compressor):
    """Uncompressed multi-step local SGD transmitting the weighted
    weight delta (the communication-frugal baseline)."""
    name = "fedavg"
    local_sgd = True

    def wire_floats(self, cfg) -> int:
        return cfg.grad_size

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        return _fserver()._fedavg(gradient, Vvelocity, Verror, cfg,
                                  lr, key)


class UncompressedCompressor(Compressor):
    """Dense single-step SGD — the no-compression upper bound."""
    name = "uncompressed"

    def wire_floats(self, cfg) -> int:
        return cfg.grad_size

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        return _fserver()._uncompressed(gradient, Vvelocity, Verror,
                                        cfg, lr, key)

"""PowerSGD: rank-r power-iteration compression (ISSUE 19 plugin #1).

Vogels et al., "PowerSGD: Practical Low-Rank Gradient Compression in
Distributed Optimization" (PAPERS.md): reshape the flat [D] update
into a near-square [m, n] matrix M, run ONE warm-started power
iteration —

    P = M @ Q_prev          # [m, r]
    P_hat = orth(P)         # Gram-Schmidt orthonormalization
    Q_new = M^T @ P_hat     # [n, r]

— transmit the (m + n) * r factor floats, and carry the low-rank
residual M - P_hat @ Q_new^T in the client's error-feedback
accumulator. The warm-started Q_new is PER-CLIENT compressor state:
it rides the existing [population, D] velocity block (validate()
forces local_momentum == 0, so the block is free) through the PR-9
cohort gather/scatter pair, the crows_* checkpoint payloads, and the
screened/dropped keep-mask merge — which is exactly what makes
screened == dropped and crash->resume bit-exactness hold for the Q
state with zero new machinery.

Adaptation to this engine's topology (every client's transmit is
summed by ONE psum): the factorization is per-client and the client
DECODES its own low-rank approximation to a dense [D] vector before
the sum — the wire in a real deployment carries the (m+n)r factor
floats, so that is what wire_floats/wire_bytes bill, precisely the
convention local_topk already uses (k-sparse payload billed at k
floats, transmitted dense in simulation). Aggregation-side the dense
transmit composes unchanged with the PR-16 admission screen (finite +
norm checks over a dense vector) and the PR-17 robust aggregators
(order statistics over [N, D] client updates).

Fresh clients (all-zero Q row) warm-start from a deterministic
Gaussian init drawn on the registered "powersgd" PRNG domain folded
into the per-client round key — deterministic in (seed, round,
client), so replay and resume are bit-exact.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from commefficient_tpu.compress.base import Compressor


def factor_shape(d: int):
    """The near-square [m, n] factorization shape for a flat [D]
    update: n = isqrt(d), m = ceil(d / n) — m * n >= d >= n * n, so
    m >= n and the rank bound is min(m, n) = n."""
    n = max(1, math.isqrt(d))
    m = -(-d // n)
    return m, n


def orthonormalize(P, eps=1e-8):
    """Column-wise modified Gram-Schmidt with an eps-guarded norm
    (rank is a small static constant, so the loop unrolls in the
    trace). A degenerate column (zero after projection) comes out as
    a tiny-norm direction instead of NaN — its contribution to
    P_hat @ Q^T is then ~0, and the residual lands in error
    feedback like any other compression loss."""
    cols = []
    for i in range(P.shape[1]):
        c = P[:, i]
        for q in cols:
            c = c - jnp.dot(q, c) * q
        c = c / jnp.maximum(jnp.linalg.norm(c), eps)
        cols.append(c)
    return jnp.stack(cols, axis=1)


class PowerSGDCompressor(Compressor):
    name = "powersgd"

    # ---- static specs -------------------------------------------------
    def state_shape(self, cfg):
        # server state is dense [D]: the decoded aggregate rides plain
        # virtual momentum, like local_topk's server side
        return (cfg.grad_size,)

    def wire_floats(self, cfg) -> int:
        m, n = factor_shape(cfg.grad_size)
        return (m + n) * cfg.powersgd_rank

    def has_errors(self, cfg) -> bool:
        return True   # validate() forces error_type == "local"

    def has_velocities(self, cfg) -> bool:
        return True   # the warm-started Q factor rides this block

    def validate(self, cfg) -> None:
        if cfg.powersgd_rank < 1:
            raise ValueError(
                f"powersgd_rank={cfg.powersgd_rank} must be >= 1")
        if cfg.error_type != "local":
            raise ValueError(
                "powersgd requires --error_type local: the low-rank "
                "residual M - P Q^T is per-client error feedback "
                "(compress/powersgd.py)")
        if cfg.local_momentum != 0:
            raise ValueError(
                "powersgd requires local_momentum == 0: the per-client "
                "velocity block carries the warm-started Q factor "
                "(compress/powersgd.py)")
        if cfg.grad_size > 0:
            m, n = factor_shape(cfg.grad_size)
            if cfg.powersgd_rank > n:
                raise ValueError(
                    f"powersgd_rank={cfg.powersgd_rank} exceeds the "
                    f"rank bound min(m, n)={n} of the "
                    f"[{m}, {n}] factorization of grad_size="
                    f"{cfg.grad_size}")

    # ---- traced hooks -------------------------------------------------
    def residual(self, cfg, to_transmit, error, velocity, key=None):
        """to_transmit IS the error accumulator here (error_type ==
        local, momentum off => local_step set error += g and
        to_transmit = error): factor it, transmit the low-rank
        approximation, keep the residual as the new error carry and
        Q_new as the new velocity carry."""
        from commefficient_tpu.analysis.domains import domain
        D = cfg.grad_size
        m, n = factor_shape(D)
        r = cfg.powersgd_rank
        M = jnp.pad(to_transmit, (0, m * n - D)).reshape(m, n)

        q_flat = velocity[:n * r]
        # warm start: a fresh client's Q row is all-zero; substitute a
        # deterministic Gaussian init (registered "powersgd" domain on
        # the per-client round key — bit-exact on replay/resume)
        q_key = jax.random.fold_in(key, domain("powersgd"))
        q_init = jax.random.normal(q_key, (n, r), jnp.float32)
        fresh = jnp.sum(q_flat * q_flat) == 0
        Q_prev = jnp.where(fresh, q_init, q_flat.reshape(n, r))

        P_hat = orthonormalize(M @ Q_prev)          # [m, r]
        Q_new = M.T @ P_hat                         # [n, r]
        approx = (P_hat @ Q_new.T).reshape(-1)[:D]  # client-side decode

        new_error = to_transmit - approx            # low-rank residual
        new_velocity = jnp.zeros_like(velocity).at[:n * r].set(
            Q_new.reshape(-1))
        return approx, new_error, new_velocity

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        # clients already decoded their factors to dense; the server
        # side is plain dense virtual momentum (local_topk's server
        # math over an already-compressed aggregate). Lazy import:
        # federated/__init__ pulls the whole engine, and config's spec
        # properties import this package.
        from commefficient_tpu.federated.server import ServerUpdate
        rho = cfg.virtual_momentum
        Vvelocity = gradient + rho * Vvelocity
        return ServerUpdate(Vvelocity * lr, Vvelocity, Verror, None)

"""The Compressor plugin interface (ISSUE 19).

Before this subsystem the five client->server update modes were
hard-wired through ``federated/client.py`` / ``federated/server.py`` /
``federated/round.py`` as inline ``cfg.mode == ...`` branches, and the
accounting / audit / bench surfaces each re-derived the per-mode wire
geometry by hand — adding a compression scheme was surgery across a
dozen files. A ``Compressor`` packages everything the engine needs to
know about one scheme:

static specs (host-side, pure config math — what round.py uses to
pre-allocate cohort operands and graftaudit/graftmesh use to trace the
plugin's programs):

  * ``state_shape(cfg)``   — shape of the server accumulator blocks
                             (ServerState.Vvelocity / .Verror);
  * ``wire_floats(cfg)``   — floats on the wire per participating
                             client per round (the analytic payload);
  * ``wire_bytes(cfg)``    — the BYTES the CommAccountant bills per
                             client per round, at the realized wire
                             dtype;
  * ``has_errors(cfg)`` / ``has_velocities(cfg)`` — whether the
    per-client [population, D] error / velocity blocks are tracked
    (the PR-9 gather/scatter pair and checkpoint ``crows_*`` payloads
    key off these);
  * ``validate(cfg)``      — plugin-specific config invariants,
    raising ``ValueError`` on combinations the plugin does not
    compose with (Config.validate dispatches here).

traced hooks (the four seams of the jitted round; every default
implementation is the IDENTITY or a pure delegation, so the five
classic plugins trace byte-identical programs to the pre-plugin
engine):

  * ``encode(cfg, grad, key)`` — per-client, inside forward_grad: the
    mean gradient -> the wire-space quantity (sketch table for the
    sketch-like plugins; dense pass-through otherwise);
  * ``residual(cfg, to_transmit, error, velocity, key)`` — per-client,
    at the end of local_step AFTER count scaling and error/momentum
    accumulation: final wire payload + the error-feedback carry
    (local_topk's sparsify-and-mask, PowerSGD's low-rank
    factorization, dp_sketch's sensitivity clip live here);
  * ``post_aggregate(cfg, transmit, round_key)`` — once per round on
    the psum'd aggregate, before the divide-by-total (dp_sketch's
    calibrated Gaussian noise lives here);
  * ``decode(cfg, gradient, Vvelocity, Verror, lr, key)`` — the
    server aggregation/decompression step -> ``ServerUpdate``.

Class attributes route the engine's remaining static branches:
``local_sgd`` (fedavg-style multi-step local training instead of one
gradient step) and ``sketch_like`` (the wire quantity is an [r, c]
count-sketch table).

Registration: instantiate and pass to ``compress.register`` (the
modules in this package do it at import). ``Config.validate`` rejects
unregistered mode names, and the registry is asserted to cover
exactly ``config.MODES``.
"""
from __future__ import annotations

from typing import Optional, Tuple


class Compressor:
    """Base plugin: the identity/dense scheme every hook defaults to.

    Subclasses override only the seams their scheme touches — every
    hook left at the default adds ZERO operations to the traced round
    programs, which is what keeps the five migrated classic modes
    bit-identical to the pre-plugin engine.
    """

    #: registry key == Config.mode value
    name: str = ""
    #: fedavg-style: one_client runs the multi-step local-SGD path
    #: (fedavg_step) instead of the single-gradient local_step, and
    #: the straggler work fraction is a completed-steps budget rather
    #: than an example-mask truncation
    local_sgd: bool = False
    #: the wire quantity is the [num_rows, num_cols] count-sketch
    #: table (server state and aggregation live in table space)
    sketch_like: bool = False

    # ---- static specs (host-side config math) -------------------------
    def state_shape(self, cfg) -> Tuple[int, ...]:
        """Shape of the server accumulator blocks for this scheme."""
        if self.sketch_like:
            return (cfg.num_rows, cfg.num_cols)
        return (cfg.grad_size,)

    def wire_floats(self, cfg) -> int:
        """Floats on the wire per participating client per round."""
        raise NotImplementedError

    def wire_bytes(self, cfg) -> int:
        """Bytes the accountant bills per participating client per
        round, at the realized wire dtype (f32 unless the plugin
        quantizes its payload)."""
        return 4 * self.wire_floats(cfg)

    def has_errors(self, cfg) -> bool:
        """Whether the per-client [population, D] error block is
        tracked (gathered/scattered/checkpointed)."""
        return cfg.error_type == "local"

    def has_velocities(self, cfg) -> bool:
        """Whether the per-client [population, D] velocity block is
        tracked. PowerSGD repurposes it for the warm-started Q
        factor, so this is a plugin decision, not just a momentum
        check."""
        return cfg.local_momentum > 0

    def validate(self, cfg) -> None:
        """Raise ValueError on config combinations this plugin does
        not support. Called from Config.validate AFTER the generic
        invariants, so plugins may assume a structurally sane
        config."""

    # ---- traced hooks (the four round seams) --------------------------
    def encode(self, cfg, grad, key=None):
        """forward_grad seam: the client's mean gradient -> the
        wire-space quantity. Default: dense pass-through (zero traced
        ops)."""
        return grad

    def residual(self, cfg, to_transmit, error, velocity, key=None):
        """local_step seam, after count scaling and error/momentum
        accumulation: returns (wire payload, new error carry, new
        velocity carry). Default: transmit everything, carries
        unchanged (zero traced ops)."""
        return to_transmit, error, velocity

    def post_aggregate(self, cfg, transmit, round_key):
        """round_step seam: the psum'd aggregate before the
        divide-by-total. Default: identity (zero traced ops)."""
        return transmit

    def decode(self, cfg, gradient, Vvelocity, Verror, lr, key=None):
        """Server aggregation/decompression -> ServerUpdate
        (federated/server.ServerUpdate). The classic plugins delegate
        to the existing server helpers verbatim."""
        raise NotImplementedError

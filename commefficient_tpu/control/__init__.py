"""commefficient_tpu.control — plan-riding feedback controllers
(ISSUE 20).

Closes the telemetry → scheduler → pipeline loop: PR 13 built the
complete measurement substrate and PR 17 proved the one safe pattern
for acting on it (controller state under the scheduler checkpoint,
the adjusted value a journaled RoundPlan wire field, replayed — never
recomputed — on restart or takeover). This package promotes that
pattern into a subsystem:

  base.py       Controller contract + ControllerBank composition
  screen.py     AdaptiveScreenController (PR 17, migrated verbatim)
  speed.py      cohort speed-matching → async admission deferral
  span.py       adaptive span cadence over a traced palette
  staleness.py  estimate-residual-driven staleness decay

Wire fields are registered in analysis/domains.CONTROL_FIELDS
(import-time uniqueness assert + graftlint GL014 AST re-proof);
`make_bank` is the single config → bank factory both drivers reach
through FedModel — it returns None when no controller flag is set, so
default runs construct nothing and stay bit-identical to pre-PR.
"""
from __future__ import annotations

from commefficient_tpu.control.base import (
    Adjustment, Controller, ControllerBank,
)
from commefficient_tpu.control.screen import AdaptiveScreenController
from commefficient_tpu.control.span import SpanCadenceController
from commefficient_tpu.control.speed import SpeedMatchController
from commefficient_tpu.control.staleness import StalenessDecayController

__all__ = [
    "Adjustment", "AdaptiveScreenController", "Controller",
    "ControllerBank", "SpanCadenceController", "SpeedMatchController",
    "StalenessDecayController", "make_bank",
]


def make_bank(cfg):
    """Build the run's ControllerBank from config flags, or None when
    no bank-managed controller is enabled (the default — the loop then
    constructs nothing and runs bit-identical to a pre-controller
    build). The screen controller is NOT bank-managed: it predates the
    bank and keeps its dedicated RoundScheduler.screen_ctl wiring."""
    controllers = []
    if cfg.speed_match:
        controllers.append(SpeedMatchController(cfg))
    if cfg.span_palette:
        controllers.append(SpanCadenceController(cfg))
    if cfg.adapt_staleness:
        controllers.append(StalenessDecayController(cfg))
    if not controllers:
        return None
    return ControllerBank(controllers)

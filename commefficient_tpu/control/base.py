"""Controller base contract + ControllerBank (ISSUE 20).

PR 17's AdaptiveScreenController proved the ONE safe shape for a
feedback controller inside a replay-exact engine, and this module
promotes that shape from a one-off into the subsystem contract:

  * OBSERVE telemetry-derived signals on the host — at plan-stamp
    (draw) time for wall-clock signals like throughput EMAs and span
    cadence, at round-commit time for device-deterministic signals
    like the estimate-residual metric;
  * emit a BOUNDED adjustment (multiplicative step, clamped to
    configured [lo, hi], f32-rounded so the journaled plan, the
    install digest, and any traced operand all carry the identical
    value);
  * RIDE the adjusted value on a registered RoundPlan wire field
    (analysis/domains.CONTROL_FIELDS — uniqueness asserted at import
    time and re-proven pure-AST by graftlint GL014), journaled in the
    write-ahead `schedule` event and digest-covered like every other
    plan field;
  * REPLAY, never recompute: a crash-resume or coordinator takeover
    installs the journaled plan bytes verbatim, and `install()` adopts
    the plan-carried value as the live state — so the adjustment
    trajectory is a pure function of the durable plan stream, not of
    any process's local clock;
  * serialize state under the scheduler checkpoint (sched_* keys,
    `ctl_<name>_<key>` namespace) so a resumed run continues the
    trajectory from the boundary.

Adjustments NEVER touch the traced programs: every controller output
is a host-side value riding operands the round programs already carry
(work fractions, the async-admit decay, the span length the staging
loop flushes at) — the standing three-programs / zero-new-programs
contract for defaults holds, and `make_bank` returns None when no
controller flag is set, keeping the default loop bit-identical.
"""
from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from commefficient_tpu.analysis.domains import CONTROL_FIELDS

__all__ = ["Adjustment", "Controller", "ControllerBank"]


class Adjustment(NamedTuple):
    """One journaled controller move: the payload of a `control`
    journal event (telemetry/journal.py validates the schema)."""
    controller: str   # Controller.NAME
    round_idx: int    # the round the adjustment was decided at
    signal: float     # the observed signal that drove the move
    old: float        # value before (f32-rounded)
    new: float        # value after (f32-rounded)
    clamped: bool     # True when the raw step hit a configured bound


class Controller:
    """Base class: one bounded, plan-riding, replay-exact knob.

    Subclasses set NAME (journal identity) and WIRE_FIELD (the
    RoundPlan `controls` key — MUST be registered to NAME in
    analysis/domains.CONTROL_FIELDS; the ControllerBank asserts it and
    graftlint GL014 re-proves it pure-AST), list their persisted
    attributes in STATE_KEYS, and override the hooks they need:

      * stamp(round_idx, ids, ex, tracker) — draw-time: observe
        wall-clock scheduling signals, adjust, and return the value to
        ride the plan (plus an optional per-slot work composition and
        the Adjustment, if any). Runs ONLY on a fresh coordinator
        round — followers and replays install instead.
      * observe_commit(round_idx, signals) — commit-time: adjust from
        device-deterministic signals (metric values). Runs on every
        committed round, replayed rounds included — deterministic
        signals reproduce the identical trajectory.
      * install(value) — adopt a plan-carried value as live state (a
        broadcast or journaled plan always wins over local state).
    """

    NAME = ""
    WIRE_FIELD = ""
    STATE_KEYS: Tuple[str, ...] = ()
    # True for the controller that owns the staging loop's span size
    # (the bank routes the drivers' span_cap queries to it)
    provides_span_cap = False
    # True when the controller's state advances at round-COMMIT time
    # (collect order) rather than draw time: a pipelined span
    # checkpoint must then save the live-at-save state — the
    # dispatch-time snapshot predates the previous span's collect —
    # exactly the accountant's save discipline (scanloop.
    # make_span_checkpoint merges ControllerBank.commit_state_dict)
    COMMIT_STATE = False

    # ---------------- value plumbing ----------------------------------
    @staticmethod
    def _f32(x) -> float:
        """f32-round a host float so the journaled plan, the digest,
        and any traced operand agree bit-for-bit."""
        return float(np.float32(x))

    def plan_value(self):
        """The value the NEXT stamped plan rides (f32-rounded floats;
        ints pass through exact)."""
        raise NotImplementedError

    def install(self, value) -> None:
        """Adopt a plan-carried value (broadcast / journaled replay):
        the durable plan stream is the authoritative trajectory, so
        the live state follows it — never the other way around."""
        raise NotImplementedError

    # ---------------- observation hooks -------------------------------
    def stamp(self, round_idx: int, ids: np.ndarray, ex: np.ndarray,
              tracker) -> Tuple[object, Optional[np.ndarray],
                                Optional[Adjustment]]:
        """Draw-time hook (fresh coordinator rounds only). Returns
        (wire value, optional [W] work-fraction composition riding
        plan.work, optional Adjustment). Default: stamp the current
        value, no work, no move."""
        del round_idx, ids, ex, tracker
        return self.plan_value(), None, None

    def observe_commit(self, round_idx: int,
                       signals: dict) -> Optional[Adjustment]:
        """Commit-time hook, fed EVERY committed round (replays
        included): signals must be device-deterministic so a replayed
        round re-observes identically. Default: no-op."""
        del round_idx, signals
        return None

    def feed_span(self, round_idx: int, n_rounds: int,
                  seconds: float) -> Optional[Adjustment]:
        """Span-collect hook (wall-clock span timing). Default:
        no-op."""
        del round_idx, n_rounds, seconds
        return None

    # ---------------- checkpoint round-trip ---------------------------
    def _state_key(self, key: str) -> str:
        return f"ctl_{self.NAME}_{key}"

    def state_dict(self) -> dict:
        out = {}
        for key in self.STATE_KEYS:
            out[self._state_key(key)] = np.asarray(getattr(self, key))
        return out

    def load_state_dict(self, state: dict) -> None:
        # legacy checkpoints (pre-controller) carry no ctl_* keys:
        # keep the config-derived start point
        for key in self.STATE_KEYS:
            full = self._state_key(key)
            if full not in state:
                continue
            cur = getattr(self, key)
            v = np.asarray(state[full])
            if isinstance(cur, bool) or isinstance(cur, np.ndarray):
                setattr(self, key, v)
            elif isinstance(cur, int):
                setattr(self, key, int(v))
            elif isinstance(cur, float):
                setattr(self, key, float(v))
            else:
                setattr(self, key, v)


class ControllerBank:
    """Ordered composition of controllers for one run.

    One instance per run, created by FedModel (control.make_bank) and
    shared with the RoundScheduler (attach_scheduler) — the scheduler
    stamps every fresh coordinator plan through it, the model installs
    plan-carried values and feeds commit/span observations, and its
    merged state rides the scheduler's sched_* checkpoint keys.
    Adjustments queue here until the model drains them into `control`
    journal events (take_events), so the bank itself stays
    journal-agnostic.
    """

    def __init__(self, controllers):
        self.controllers: List[Controller] = list(controllers)
        self._by_field: Dict[str, Controller] = {}
        self._span_ctl: Optional[Controller] = None
        for c in self.controllers:
            if CONTROL_FIELDS.get(c.NAME) != c.WIRE_FIELD:
                raise ValueError(
                    f"controller {c.NAME!r} rides wire field "
                    f"{c.WIRE_FIELD!r}, but analysis/domains."
                    f"CONTROL_FIELDS registers "
                    f"{CONTROL_FIELDS.get(c.NAME)!r} — register the "
                    "field before shipping the controller")
            if c.WIRE_FIELD in self._by_field:
                raise ValueError(
                    f"two controllers share wire field "
                    f"{c.WIRE_FIELD!r}: {self._by_field[c.WIRE_FIELD].NAME!r} "
                    f"and {c.NAME!r}")
            self._by_field[c.WIRE_FIELD] = c
            if c.provides_span_cap:
                self._span_ctl = c
        self._events: List[Adjustment] = []

    def __len__(self) -> int:
        return len(self.controllers)

    @property
    def names(self) -> list:
        return [c.NAME for c in self.controllers]

    # ---------------- scheduler side ----------------------------------
    def stamp_plan(self, plan, ids: np.ndarray, ex: np.ndarray,
                   tracker):
        """Fresh-coordinator stamp: run every controller's draw-time
        hook, min-compose any work fractions onto the plan (the same
        host-side merge deadline truncation rides), and seal the wire
        values into plan.controls. Queued adjustments journal at the
        model's next drain."""
        controls = {}
        work = plan.work
        for c in self.controllers:
            value, cwork, adj = c.stamp(int(plan.round_idx), ids, ex,
                                        tracker)
            controls[c.WIRE_FIELD] = value
            if cwork is not None:
                cwork = np.asarray(cwork, np.float32)
                work = (cwork if work is None
                        else np.minimum(np.asarray(work, np.float32),
                                        cwork))
            if adj is not None:
                self._events.append(adj)
        return plan._replace(work=work, controls=controls)

    # ---------------- model side --------------------------------------
    def install(self, controls: dict) -> None:
        """Adopt a plan's carried values (broadcast / replay / the
        coordinator's own round-tripped stamp — idempotent there)."""
        for field, value in controls.items():
            c = self._by_field.get(field)
            if c is not None:
                c.install(value)

    def observe_commit(self, round_idx: int, signals: dict) -> None:
        for c in self.controllers:
            adj = c.observe_commit(int(round_idx), signals)
            if adj is not None:
                self._events.append(adj)

    def feed_span(self, round_idx: int, n_rounds: int,
                  seconds: float) -> None:
        for c in self.controllers:
            adj = c.feed_span(int(round_idx), int(n_rounds),
                              float(seconds))
            if adj is not None:
                self._events.append(adj)

    def take_events(self) -> List[Adjustment]:
        """Drain queued adjustments (the model journals each as a
        `control` event)."""
        events, self._events = self._events, []
        return events

    # ---------------- staging-loop span size --------------------------
    def span_cap(self, default: int) -> int:
        """The span size the staging loop should flush at next (the
        span-cadence controller's live pick, or `default`)."""
        if self._span_ctl is None:
            return int(default)
        return int(self._span_ctl.span_cap())

    def tail_cap(self, leftover: int) -> int:
        """Largest already-traced span size <= leftover, for the
        stream-tail decomposition (palette includes 1, so this always
        exists); identity without a span controller."""
        if self._span_ctl is None:
            return int(leftover)
        return int(self._span_ctl.tail_cap(int(leftover)))

    # ---------------- checkpoint round-trip ---------------------------
    def state_dict(self) -> dict:
        out = {}
        for c in self.controllers:
            out.update(c.state_dict())
        return out

    def commit_state_dict(self) -> dict:
        """State of the COMMIT_STATE controllers only — the keys a
        pipelined span checkpoint overlays live at save time (the
        boundary snapshot predates the previous span's collect, but
        commit-time state advances in span order, so the live read at
        save time is the span-consistent one — the accountant's
        discipline)."""
        out = {}
        for c in self.controllers:
            if c.COMMIT_STATE:
                out.update(c.state_dict())
        return out

    def load_state_dict(self, state: dict) -> None:
        for c in self.controllers:
            c.load_state_dict(state)

"""Adaptive span-cadence controller (ISSUE 20).

PR 10's pipelined staging loop flushes a span of staged rounds into
one scanned device program; the span length trades per-span host
overhead (checkpoint hooks, journal flushes, dispatch bookkeeping)
against staging latency, and PR 13's journal measures exactly that
trade as inter-round cadence — but the length was a static
``--scan_span``. This controller picks the span length from a small
static ``--scan_span_palette`` instead:

  * every collected span feeds (n_rounds, wall seconds) → the
    controller tracks a per-palette-entry EMA of SECONDS PER ROUND
    (the journal's cadence signal, attributed to the span length that
    produced it);
  * warmup CYCLES through the palette once, so every palette entry's
    scanned program is traced exactly once before steady state — the
    palette is the complete shape vocabulary, steady state stays
    zero-recompile, and the existing ``compile_warning`` gate
    enforces it;
  * after warmup the pick is the argmin-EMA entry; the stream tail
    (fewer rounds left than the pick) decomposes greedily over the
    palette — largest entry that fits, down to 1 (Config.validate
    requires 1 ∈ palette) — so a tail NEVER traces a new shape.

The pick rides the plan (`scan_span` wire field). Span timing is
wall-clock, so like speed-matching the DECISION is only ever taken on
the live fresh path, and replayed rounds install() the journaled
pick: a resumed run reproduces the original span trajectory from the
plan stream, while its live EMAs keep learning from fresh
measurements for post-replay picks.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from commefficient_tpu.control.base import Adjustment, Controller

__all__ = ["SpanCadenceController"]

# EMA coefficient for per-entry seconds-per-round: heavy enough to
# track load shifts, light enough to ride out one noisy span
_CADENCE_ALPHA = 0.5


class SpanCadenceController(Controller):
    """Pick the staging-loop span length from a traced palette."""

    NAME = "span_cadence"
    WIRE_FIELD = "scan_span"
    STATE_KEYS = ("choice", "spans_observed", "ema")
    provides_span_cap = True

    def __init__(self, cfg):
        self.palette = tuple(int(p) for p in cfg.span_palette)
        if not self.palette:
            raise ValueError("SpanCadenceController needs a non-empty "
                             "--scan_span_palette")
        self.choice = int(self.palette[0])
        self.spans_observed = 0
        # seconds-per-round EMA per palette entry; NaN = not yet tried
        self.ema = np.full(len(self.palette), np.nan, np.float64)

    def plan_value(self) -> int:
        return int(self.choice)

    def install(self, value) -> None:
        self.choice = int(value)

    # ---------------- staging-loop queries ----------------------------
    def span_cap(self) -> int:
        """The span length the NEXT staged span should flush at."""
        return int(self.choice)

    def tail_cap(self, leftover: int) -> int:
        """Largest palette entry <= leftover, for the stream-tail
        decomposition (1 ∈ palette guarantees existence)."""
        fits = [p for p in self.palette if p <= int(leftover)]
        if not fits:
            return int(min(self.palette))
        return int(max(fits))

    # ---------------- observation -------------------------------------
    def feed_span(self, round_idx: int, n_rounds: int,
                  seconds: float) -> Optional[Adjustment]:
        """Feed one collected span's (length, wall seconds); returns
        an Adjustment when the pick moves. `round_idx` is the span's
        last round (the journal anchor)."""
        if int(n_rounds) <= 0:
            return None
        per_round = float(seconds) / float(n_rounds)
        if int(n_rounds) in self.palette:
            i = self.palette.index(int(n_rounds))
            if np.isnan(self.ema[i]):
                self.ema[i] = per_round
            else:
                self.ema[i] = (_CADENCE_ALPHA * per_round
                               + (1.0 - _CADENCE_ALPHA) * self.ema[i])
        self.spans_observed += 1
        old = int(self.choice)
        untried = [p for i, p in enumerate(self.palette)
                   if np.isnan(self.ema[i])]
        if untried:
            # warmup: trace every palette entry once before letting
            # the EMAs pick — steady state then replays known shapes
            new = int(untried[0])
        else:
            new = int(self.palette[int(np.argmin(self.ema))])
        self.choice = new
        if new != old:
            # a palette pick is bounded by construction — the clamp
            # bit is always False here
            return Adjustment(self.NAME, int(round_idx),
                              float(per_round), float(old), float(new),
                              False)
        return None

"""Adaptive norm-screen controller (ISSUE 17 → migrated, ISSUE 20).

This is PR 17's `AdaptiveScreenController`, moved from
scheduler/__init__.py onto the `Controller` base unchanged in
behavior: same config knobs, same f32 step/clamp arithmetic, same
legacy (unprefixed) checkpoint keys, same `observe(round_idx,
n_screened, n_cohort)` call the model's screening commit path already
makes — tests/test_control.py proves the `screen_mult` trajectory and
`screen_adapt` journal stream are bit-identical to the pre-migration
build. It keeps riding `RoundScheduler.screen_ctl` (its wiring
predates the ControllerBank and its wire field `screen_mult` is a
top-level RoundPlan field, not a `controls` entry), but its NAME /
WIRE_FIELD registration now flows through the same CONTROL_FIELDS
registry and GL014 lint as the bank-managed controllers.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from commefficient_tpu.control.base import Controller

__all__ = ["AdaptiveScreenController"]


class AdaptiveScreenController(Controller):
    """Closed-loop tuner for the norm-screen threshold (ISSUE 17).

    PR 16's update screening rejects client updates whose l2 norm
    exceeds ``screen_norm_mult`` times the cohort median — a STATIC
    multiplier, so an operator has to guess how aggressive the screen
    should be before seeing the run. This controller closes the loop:
    it watches the journaled per-round screened rate and nudges the
    multiplier multiplicatively toward ``--target_screened_rate``
    (observed rate above target → loosen, below → tighten), clamped to
    [screen_mult_min, screen_mult_max].

    Determinism contract: every adjustment is pure f32 arithmetic on
    journal-materialized integer counts — no wall clock, no RNG — and
    the multiplier each round dispatches with RIDES THE ROUNDPLAN
    (``RoundPlan.screen_mult``), coordinator-broadcast under
    ``--plan_transport`` and replayed (not recomputed) from the
    write-ahead journal on a restart or takeover. The traced program
    never changes: the screen operand PR 16 already threads into the
    jitted round carries the live multiplier as its VALUE, and its
    plan-digest coverage (install_digest's screen_on field) extends to
    the multiplier for free. ``screen_mult_min`` must stay > 1 so the
    adapted value can never collide with the screen-off sentinel 0.

    One instance per run, created by FedModel and shared with the
    RoundScheduler (attach_scheduler): the model consults it for
    transport-free dispatch, the scheduler stamps it into broadcast
    plans. Its state rides the scheduler's sched_* checkpoint keys so
    a resumed run continues the trajectory bit-exactly.
    """

    NAME = "screen_adapt"
    WIRE_FIELD = "screen_mult"
    # legacy key names (pre-ControllerBank): checkpoints written by
    # PR 17..19 builds must keep restoring, so the base class's
    # ctl_<name>_<key> namespace does NOT apply here
    STATE_KEYS = ("screen_mult", "screen_rounds_observed")

    def __init__(self, cfg):
        self.target = float(cfg.target_screened_rate)
        self.step = float(cfg.screen_adapt_step)
        self.lo = float(cfg.screen_mult_min)
        self.hi = float(cfg.screen_mult_max)
        self.mult = float(np.float32(
            min(max(float(cfg.screen_norm_mult), self.lo), self.hi)))
        self.rounds_observed = 0

    def plan_mult(self) -> float:
        """The multiplier the NEXT round dispatches with — f32-rounded
        so the journaled plan, the install digest, and the traced
        screen operand all carry the identical value."""
        return float(np.float32(self.mult))

    # Controller-contract aliases
    def plan_value(self) -> float:
        return self.plan_mult()

    def install(self, value) -> None:
        self.mult = float(value)

    def observe(self, round_idx: int, n_screened: int,
                n_cohort: int) -> Optional[tuple]:
        """Feed one committed round's observed screened count (EVERY
        round, zero included — the controller's trajectory is a pure
        function of the observation stream, so skipping quiet rounds
        would desync a resumed run). Returns (old_mult, new_mult,
        rate) when the threshold moved, else None."""
        del round_idx  # trajectory is stream-positional, not indexed
        self.rounds_observed += 1
        rate = float(n_screened) / float(max(int(n_cohort), 1))
        old = self.plan_mult()
        if rate > self.target:
            new = min(old * (1.0 + self.step), self.hi)
        elif rate < self.target:
            new = max(old / (1.0 + self.step), self.lo)
        else:
            new = old
        new = float(np.float32(new))
        self.mult = new
        if new != old:
            return (old, new, rate)
        return None

    def state_dict(self) -> dict:
        return {"screen_mult": np.float64(self.mult),
                "screen_rounds_observed": np.int64(
                    self.rounds_observed)}

    def load_state_dict(self, state: dict) -> None:
        # legacy checkpoints (pre-17) carry no controller keys: keep
        # the config-derived start point
        if "screen_mult" in state:
            self.mult = float(np.asarray(state["screen_mult"]))
            self.rounds_observed = int(np.asarray(
                state.get("screen_rounds_observed", 0)))

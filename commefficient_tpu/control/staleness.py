"""Adaptive staleness-decay controller (ISSUE 20).

The async admission buffer (PR 2/PR 10) discounts a deferred slot's
late contribution by ``async_staleness_decay ** rounds_late`` — a
static prior on how fast stale gradients rot. PR 13's measurement
substrate computes the actual rot-rate proxy every round: the
``estimate_residual`` metric, ``error_l2 / (error_l2 + update_l2)``,
the fraction of each round's information the sketch left behind. A
noisy estimate pipeline means stale work is built on an even shakier
base, so this controller closes the loop:

  * at round COMMIT the model feeds the round's estimate_residual;
    residual above ``--staleness_target`` tightens the decay
    (discount late work harder), below loosens it, multiplicative
    steps clamped to [staleness_decay_min, staleness_decay_max];
  * the adjusted decay rides the plan (`staleness_decay` wire field)
    and the model applies the PLAN-CARRIED value to the admission
    buffer at compose time — the discount each round actually uses is
    digest-covered and follower-identical, never a process-local
    read.

The signal is DEVICE-DETERMINISTIC (a replayed round re-observes the
identical residual), but commit-time state read at DRAW time is not
automatically replay-safe: under the pipelined staging loop, "which
rounds have committed when round r is drawn" depends on the span
decomposition and on where a resume seam lands — both wall-clock.
So the stamp is FIXED-LAG instead of live: each commit appends
(round, decay) to a small ring, and the plan value for round r is
the ring entry at ``r - lag``, where the lag is the config-derived
worst case of how far staging runs ahead of commits (1 for the
synchronous per-round loop — the pre-existing live semantics — up to
2x the largest span under ``--pipeline``). The stamped trajectory is
then a pure function of per-round committed signals, invariant to
span decomposition and prefetch depth, which is what makes a
pipelined crash-resume bit-exact (tests/test_control.py).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from commefficient_tpu.control.base import Adjustment, Controller

__all__ = ["StalenessDecayController"]


def _observe_lag(cfg) -> int:
    """Rounds between a commit-time observation and the first stamped
    plan allowed to see it. Must be >= the worst-case staging runahead
    so the ring lookup never races a collect: 1 in the synchronous
    per-round loop (draws and commits strictly alternate), the span
    length for synchronous scanned staging (a whole span is drawn
    before any of it commits), and twice that under --pipeline (the
    next span stages while the previous one is still in flight)."""
    pal = tuple(getattr(cfg, "span_palette", ()) or ())
    if pal:
        horizon = max(pal)
    elif getattr(cfg, "scan_rounds", False):
        horizon = max(int(getattr(cfg, "scan_span", 0)), 1)
    else:
        horizon = 1
    return 2 * horizon if getattr(cfg, "pipeline", False) else horizon


class StalenessDecayController(Controller):
    """Tune the async admission staleness discount from the
    estimate-residual metric."""

    NAME = "staleness_decay"
    WIRE_FIELD = "staleness_decay"
    STATE_KEYS = ("decay", "rounds_observed", "ring")
    # the telemetry metric observed at commit (telemetry/metrics.py)
    SIGNAL = "estimate_residual"
    # the ring advances at COLLECT time in span order (like the
    # accountant), so a pipelined span checkpoint must carry the
    # live-at-save state, not the dispatch-time snapshot
    COMMIT_STATE = True

    def __init__(self, cfg):
        self.target = float(cfg.staleness_target)
        self.step = float(cfg.staleness_step)
        self.lo = float(cfg.staleness_decay_min)
        self.hi = float(cfg.staleness_decay_max)
        self.lag = _observe_lag(cfg)
        # fold tail: the decay after the newest observed commit
        self.decay = self._f32(
            min(max(float(cfg.async_staleness_decay), self.lo),
                self.hi))
        self.init_decay = self.decay
        self.rounds_observed = 0
        # [n, 2] (round, decay-after-commit) pairs in round order —
        # one per observed commit, pruned to the lookup horizon
        self.ring = np.zeros((0, 2), np.float64)
        # the value the last stamped/installed plan carried
        self.stamped = self.decay

    def plan_value(self) -> float:
        return self._f32(self.stamped)

    def install(self, value) -> None:
        # the plan-carried value is what the round APPLIES (the model
        # writes it into the admission buffer at compose time); the
        # fold state advances only through observe_commit, which runs
        # identically on followers and replayed rounds
        self.stamped = float(value)

    def _lagged(self, round_idx: int) -> float:
        """Decay after the newest commit at or before
        ``round_idx - lag`` (the initial value before any qualifies).
        The lag guarantees that commit has always been observed by
        draw time, on every engine path."""
        k = int(round_idx) - self.lag
        ring = np.asarray(self.ring, np.float64).reshape(-1, 2)
        eligible = ring[ring[:, 0] <= k]
        if len(eligible) == 0:
            return self._f32(self.init_decay)
        return self._f32(eligible[-1, 1])

    def stamp(self, round_idx, ids, ex, tracker):
        del ids, ex, tracker
        self.stamped = self._lagged(round_idx)
        return self.plan_value(), None, None

    def observe_commit(self, round_idx: int,
                       signals: dict) -> Optional[Adjustment]:
        resid = signals.get(self.SIGNAL)
        if resid is None:
            return None
        self.rounds_observed += 1
        resid = float(resid)
        old = self._f32(self.decay)
        new, clamped = old, False
        if resid > self.target:
            # noisy estimates: stale deferred work is even less
            # trustworthy — discount it harder
            raw = old / (1.0 + self.step)
            new, clamped = max(raw, self.lo), raw < self.lo
        elif resid < self.target:
            raw = old * (1.0 + self.step)
            new, clamped = min(raw, self.hi), raw > self.hi
        new = self._f32(new)
        self.decay = new
        # every observed commit gets a ring entry (adjusted or not),
        # so the lagged lookup lands on exact rounds and pruning can
        # never strand a lookup on the initial-value fallback
        ring = np.asarray(self.ring, np.float64).reshape(-1, 2)
        ring = np.concatenate(
            [ring, [[float(int(round_idx)), new]]], axis=0)
        keep = 4 * self.lag + 4
        self.ring = ring[-keep:]
        if new != old:
            return Adjustment(self.NAME, int(round_idx), resid,
                              old, new, bool(clamped))
        return None

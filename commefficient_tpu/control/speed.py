"""Cohort speed-matching controller (ISSUE 20).

The ROADMAP's first named control loop: the throughput tracker (PR 4)
already maintains per-client examples/sec EMAs, and the async
admission buffer (PR 2/PR 10) already knows how to carry a
partial-work slot across rounds and admit it later with a staleness
discount — but nothing connected them, so a slow cohort-mate still
dragged every round to its own pace. This controller closes that
loop as a PURE HOST-SIDE MERGE on operands the traced programs
already carry:

  * at plan-stamp time it compares each measured active client's rate
    EMA against the cohort median; clients slower than
    ``ratio × median`` get a work fraction < 1 composed onto
    plan.work (min-merge, exactly how deadline truncation rides);
  * a work fraction < 1 on a surviving slot is precisely what the
    async admission buffer defers into an ``--async_admit_rounds``
    slot — so the slow client's contribution lands a round late with
    the staleness discount instead of stalling its cohort;
  * the ratio itself is the feedback knob: the observed deferred
    fraction is nudged multiplicatively toward
    ``--speed_match_target``, clamped to [speed_ratio_min,
    speed_ratio_max] (max < 1, so a "slow" client is always strictly
    slower than the median and its fraction strictly < 1).

Replay-exactness is STRUCTURAL: rate EMAs are wall-clock-derived, so
the adjustment runs at draw time on the fresh coordinator path ONLY —
the stamped plan carries the post-adjustment ratio, followers and
replayed rounds install() the plan's value, and a resumed run's ratio
state is therefore a pure function of the journaled plan stream.
The median-threshold rule also bounds the blast radius: at most half
the measured cohort can ever sit strictly below ``ratio × median``
(the ratio cap is < 1), so a round can never defer itself empty.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from commefficient_tpu.control.base import Adjustment, Controller

__all__ = ["SpeedMatchController"]

# floor on a deferred slot's work fraction: a barely-measured client
# must still carry enough of its round for the late admit to matter
_MIN_DEFER_FRAC = 0.25


class SpeedMatchController(Controller):
    """Defer measured-slow clients into async admission slots."""

    NAME = "speed_match"
    WIRE_FIELD = "speed_ratio"
    STATE_KEYS = ("ratio", "rounds_observed")

    def __init__(self, cfg):
        self.target = float(cfg.speed_match_target)
        self.step = float(cfg.speed_match_step)
        self.lo = float(cfg.speed_ratio_min)
        self.hi = float(cfg.speed_ratio_max)
        self.ratio = self._f32(
            min(max(float(cfg.speed_ratio), self.lo), self.hi))
        self.rounds_observed = 0

    def plan_value(self) -> float:
        return self._f32(self.ratio)

    def install(self, value) -> None:
        self.ratio = float(value)

    def stamp(self, round_idx: int, ids: np.ndarray, ex: np.ndarray,
              tracker) -> Tuple[float, Optional[np.ndarray],
                                Optional[Adjustment]]:
        ex = np.asarray(ex, np.float64).reshape(-1)
        ids = np.asarray(ids).reshape(-1)
        active = ex > 0
        rates = np.asarray(tracker.examples_per_sec(ids),
                           np.float64).reshape(-1)
        # 0.0 means "never measured" — an unmeasured client is never
        # flagged slow (no evidence), and speed matching needs at
        # least two measured rates for a meaningful median
        measured = active & (rates > 0.0)
        work = None
        adj = None
        if int(measured.sum()) >= 2:
            med = float(np.median(rates[measured]))
            if med > 0.0:
                # the SIGNAL is the deferred fraction the current
                # ratio would produce; observe first, then flag under
                # the post-adjustment ratio so the stamped wire value
                # and the stamped work fractions agree
                slow = measured & (rates < self.plan_value() * med)
                signal = (float(slow.sum())
                          / float(max(int(active.sum()), 1)))
                adj = self._observe(round_idx, signal)
                slow = measured & (rates < self.plan_value() * med)
                if bool(slow.any()):
                    work = np.ones(len(ex), np.float32)
                    frac = np.maximum(rates[slow] / med,
                                      _MIN_DEFER_FRAC)
                    work[slow] = frac.astype(np.float32)
        return self.plan_value(), work, adj

    def _observe(self, round_idx: int,
                 signal: float) -> Optional[Adjustment]:
        self.rounds_observed += 1
        old = self.plan_value()
        if signal > self.target:
            # deferring too much of the cohort: tighten the slowness
            # bar so fewer clients qualify
            raw = old / (1.0 + self.step)
            new, clamped = max(raw, self.lo), raw < self.lo
        elif signal < self.target:
            raw = old * (1.0 + self.step)
            new, clamped = min(raw, self.hi), raw > self.hi
        else:
            return None
        new = self._f32(new)
        self.ratio = new
        if new != old:
            return Adjustment(self.NAME, int(round_idx), float(signal),
                              old, new, bool(clamped))
        return None

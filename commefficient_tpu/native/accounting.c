/* Native hot path for per-client download accounting.
 *
 * The expensive accounting path (reference fed_aggregator.py:251-289,
 * re-designed as change bitsets in federated/accounting.py) needs, per
 * round, the popcount of the OR of the last `s` rounds' change bitsets
 * for each distinct client staleness s.  The numpy route materializes
 * a byte-table temporary per popcount (~4x the bitset) and walks the
 * OR-prefix in Python; at GPT2 scale a bitset is ~4M words, so this
 * fused C loop (64-bit ORs + __builtin_popcountll, no temporaries) is
 * the difference between accounting being free and being a per-round
 * host stall.
 *
 * Exposed as `prefix_or_popcounts(rows, n_words, max_depth) ->
 * list[int]` where `rows` is a sequence of per-round uint32 bitset
 * buffers (oldest first, each n_words words, consumed zero-copy via
 * the buffer protocol) and result[s] = popcount(OR of the last s
 * rows), s = 0..max_depth.  Pure CPython C API (no numpy headers) so
 * it builds anywhere with a C compiler; accounting.py falls back to
 * numpy when the module is absent.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *
prefix_or_popcounts(PyObject *self, PyObject *args)
{
    PyObject *rows_seq;
    Py_ssize_t n_words, max_depth;

    if (!PyArg_ParseTuple(args, "Onn", &rows_seq, &n_words, &max_depth))
        return NULL;

    PyObject *rows = PySequence_Fast(rows_seq, "rows must be a sequence");
    if (!rows)
        return NULL;
    Py_ssize_t n_rows = PySequence_Fast_GET_SIZE(rows);

    if (n_words < 0 || max_depth < 0 || max_depth > n_rows) {
        Py_DECREF(rows);
        PyErr_SetString(PyExc_ValueError, "inconsistent geometry");
        return NULL;
    }

    uint32_t *acc = (uint32_t *)calloc((size_t)(n_words ? n_words : 1),
                                       sizeof(uint32_t));
    if (!acc) {
        Py_DECREF(rows);
        return PyErr_NoMemory();
    }

    PyObject *out = PyList_New(max_depth + 1);
    if (!out) {
        free(acc);
        Py_DECREF(rows);
        return NULL;
    }
    PyList_SET_ITEM(out, 0, PyLong_FromUnsignedLongLong(0));

    for (Py_ssize_t d = 1; d <= max_depth; d++) {
        /* fold in the d-th most recent round's bitset zero-copy and
           re-popcount; OR + popcount in 64-bit chunks */
        Py_buffer view;
        PyObject *row_obj = PySequence_Fast_GET_ITEM(rows, n_rows - d);
        if (PyObject_GetBuffer(row_obj, &view, PyBUF_C_CONTIGUOUS) < 0) {
            /* GetBuffer set the exception; view is untouched */
            free(acc);
            Py_DECREF(rows);
            Py_DECREF(out);
            return NULL;
        }
        if (view.len < n_words * 4) {
            PyBuffer_Release(&view);
            PyErr_SetString(PyExc_ValueError, "row buffer too short");
            free(acc);
            Py_DECREF(rows);
            Py_DECREF(out);
            return NULL;
        }
        const uint32_t *row = (const uint32_t *)view.buf;
        unsigned long long count = 0;
        Py_ssize_t pairs = n_words / 2;
        uint64_t *acc64 = (uint64_t *)acc;
        const uint64_t *row64 = (const uint64_t *)row;
        for (Py_ssize_t i = 0; i < pairs; i++) {
            acc64[i] |= row64[i];
            count += (unsigned long long)__builtin_popcountll(acc64[i]);
        }
        for (Py_ssize_t w = pairs * 2; w < n_words; w++) {
            acc[w] |= row[w];
            count += (unsigned long long)__builtin_popcount(acc[w]);
        }
        PyBuffer_Release(&view);
        PyObject *v = PyLong_FromUnsignedLongLong(count);
        if (!v) {
            free(acc);
            Py_DECREF(rows);
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, d, v);
    }

    free(acc);
    Py_DECREF(rows);
    return out;
}

static PyObject *
popcount_words(PyObject *self, PyObject *args)
{
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf))
        return NULL;
    const uint8_t *p = (const uint8_t *)buf.buf;
    Py_ssize_t n = buf.len;
    unsigned long long count = 0;
    Py_ssize_t chunks = n / 8;
    const uint64_t *p64 = (const uint64_t *)p;
    for (Py_ssize_t i = 0; i < chunks; i++)
        count += (unsigned long long)__builtin_popcountll(p64[i]);
    for (Py_ssize_t i = chunks * 8; i < n; i++)
        count += (unsigned long long)__builtin_popcount(p[i]);
    return PyLong_FromUnsignedLongLong(count);
}

static PyMethodDef Methods[] = {
    {"prefix_or_popcounts", prefix_or_popcounts, METH_VARARGS,
     "counts[s] = popcount(OR of last s rows) for s in 0..max_depth"},
    {"popcount_words", popcount_words, METH_VARARGS,
     "total popcount of a bytes-like buffer"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_native_accounting",
    "fused bitset accounting kernels", -1, Methods
};

PyMODINIT_FUNC
PyInit__native_accounting(void)
{
    return PyModule_Create(&moduledef);
}

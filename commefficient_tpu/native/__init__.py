"""Native (C) fast paths with pure-numpy fallbacks.

`accounting.c` fuses the OR-prefix + popcount walk of the download
accountant (see federated/accounting.py). Import `native_accounting`
from here; it is None when the extension isn't built, and callers keep
their numpy path.
"""
from __future__ import annotations

try:
    from commefficient_tpu.native import _native_accounting as native_accounting
except ImportError:  # extension not built — numpy fallback in use
    native_accounting = None

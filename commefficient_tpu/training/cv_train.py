"""CV federated training driver.

The reference driver's launch surface re-created on the TPU runtime
(reference: CommEfficient/cv_train.py — loss/metric callbacks :32-83,
epoch loop `train`/`run_batches` :85-250, loader construction
:254-287, `__main__` wiring :289-421): same flags (config.parse_args),
same loss-callback contract, same TableLogger output columns, same
communication-MiB reporting, same --test smoke shrink, NaN abort,
checkpoint and head-swap finetune. Differences are the TPU runtime
underneath (one jitted SPMD round instead of processes+NCCL) and one
addition the reference cannot express: --scan_rounds runs a whole
epoch of rounds as a single scanned device program
(FedModel.run_rounds), amortizing all host dispatch.

Run: python -m commefficient_tpu.training.cv_train --dataset_name
CIFAR10 --mode sketch --error_type virtual ...
"""
from __future__ import annotations

import contextlib
import math
import os
from typing import Optional

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from commefficient_tpu import models
from commefficient_tpu.config import Config, num_classes_of_dataset, parse_args
from commefficient_tpu.data import (
    FedCIFAR10, FedCIFAR100, FedEMNIST, FedImageNet, FedLoader,
    FedValLoader, transforms,
)
from commefficient_tpu.federated.api import FedModel, FedOptimizer
from commefficient_tpu.parallel import multihost as mh
from commefficient_tpu.utils.cache import enable_persistent_compilation_cache
from commefficient_tpu.training.scanloop import (
    make_span_checkpoint, numeric_rollback, run_scanned_rounds,
)
from commefficient_tpu.utils.checkpoint import (
    latest_checkpoint_path, load_checkpoint, load_resilient,
    save_final, save_rotating, transfer_for_finetune,
)
from commefficient_tpu.telemetry.trace import TRACE
from commefficient_tpu.utils.logging import (
    TableLogger, Timer, make_logdir,
)
from commefficient_tpu.utils.schedules import LambdaLR, PiecewiseLinear


# ---------------- loss callbacks (reference cv_train.py:32-83) -----------

def make_compute_loss(model):
    """Masked cross-entropy + accuracy under the framework's loss
    contract: loss_fn(params, (images, labels), mask) ->
    (mean loss, (mean accuracy,))."""

    def compute_loss(params, batch, mask):
        images, labels = batch
        logits = model.apply(params, images)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (nll * mask).sum() / denom
        acc = ((logits.argmax(-1) == labels) * mask).sum() / denom
        return loss, (acc,)

    return compute_loss


# ---------------- data (reference cv_train.py:254-287) -------------------

# name -> (dataset class, transform factory, --test synthetic sizes)
# (the reference routes all four CV datasets the same way,
# cv_train.py:254-287; EMNIST synthetic sizes are (writers, imgs/writer),
# ImageNet's are (train, val) — see each dataset's docstring)
_DATASETS = {
    "CIFAR10": (FedCIFAR10, transforms.cifar10_transforms, (2048, 512)),
    "CIFAR100": (FedCIFAR100, transforms.cifar100_transforms, (2048, 512)),
    "EMNIST": (FedEMNIST, transforms.femnist_transforms, (64, 16)),
    "ImageNet": (FedImageNet, transforms.imagenet_transforms, (512, 64)),
}


def get_data_loaders(cfg: Config):
    try:
        dataset_cls, transform_factory, test_sizes = _DATASETS[cfg.dataset_name]
    except KeyError:
        raise ValueError(
            f"cv_train supports {sorted(_DATASETS)}; for PERSONA use "
            f"gpt2_train (reference split is the same, cv_train.py vs "
            f"gpt2_train.py)")
    train_t, test_t = transform_factory(seed=cfg.seed)
    # --test smoke: generate a small synthetic dataset when the real
    # archives aren't on disk (the reference's --test mode likewise
    # bypasses real compute, fed_worker.py:117-122)
    synthetic = test_sizes if cfg.do_test else None
    train_set = dataset_cls(
        cfg.dataset_dir, transform=train_t, do_iid=cfg.do_iid,
        num_clients=cfg.num_clients, train=True, seed=cfg.seed,
        synthetic_examples=synthetic)
    val_set = dataset_cls(
        cfg.dataset_dir, transform=test_t, do_iid=cfg.do_iid,
        num_clients=cfg.num_clients, train=False, seed=cfg.seed,
        synthetic_examples=synthetic)
    train_loader = FedLoader(train_set, cfg.num_workers,
                             cfg.local_batch_size, seed=cfg.seed,
                             max_local_batch=cfg.max_local_batch)
    val_loader = FedValLoader(val_set, cfg.valid_batch_size,
                              num_shards=min(jax.device_count(),
                                             cfg.num_workers))
    return train_loader, val_loader


# ---------------- training loop (reference cv_train.py:85-250) -----------

def run_eval(model: FedModel, val_loader) -> tuple:
    model.train(False)
    tot_loss = tot_acc = tot_n = 0.0
    for data, mask in val_loader.batches():
        loss, acc, count = model((data, mask))
        n = count.sum()
        tot_loss += float((loss * count).sum())
        tot_acc += float((acc * count).sum())
        tot_n += float(n)
    model.train(True)
    denom = max(tot_n, 1.0)
    return tot_loss / denom, tot_acc / denom


def train(model: FedModel, opt: FedOptimizer, lr_scheduler,
          train_loader, val_loader, cfg: Config,
          loggers=(), timer: Optional[Timer] = None, log_dir: str = ""):
    timer = timer or Timer()
    # --debug_transfer_guard: forbid implicit host<->device transfers
    # in the steady-state loop — every span/round after the first
    # (which compiles) dispatches under the guard, so a hidden
    # per-round sync raises instead of silently stalling the tunnel
    guard = None
    if cfg.debug_transfer_guard:
        from commefficient_tpu.analysis.runtime import forbid_transfers
        guard = forbid_transfers
    # first dispatch of THIS PROCESS compiles (also after a resume, so
    # this is a process-local flag, not round count)
    warmed = [False]
    spe = train_loader.steps_per_epoch
    total_rounds = math.ceil(cfg.num_epochs * spe)
    # on resume, num_epochs is the TOTAL budget: rounds already done
    # (restored round_idx) count against it
    rounds_done = int(model.server.round_idx)
    epoch = rounds_done // spe
    # mid-epoch resume: fast-forward the first resumed epoch's stream
    # past the rounds already trained — sampler index math only, no
    # batch materialization (FedLoader.epoch(skip=); symmetric with
    # gpt2_train's fast-forward). With checkpointed sampler state
    # (smp_* keys restored by model.load_state), resolve_resume
    # collapses the skip to 0: the restored cursor CONTINUES the
    # stream exactly, so non-uniform sampling resumes onto the same
    # data the uninterrupted run would have fed.
    skip_rounds = train_loader.sampler.resolve_resume(
        rounds_done % spe)
    # restored mid-epoch stream: the uninterrupted run caps every
    # epoch at spe rounds, so a stream restored AT the cap was
    # abandoned right there (discard — the restored rng is all a
    # fresh epoch needs), and one restored short of the cap may only
    # be driven for the REMAINING spe - pos rounds (the scanned
    # epoch_rounds budget below subtracts resumed_pos; without the
    # subtraction a resumed epoch would overrun the cap on the same
    # permutation)
    resumed_pos = train_loader.sampler.pending_pos or 0
    if resumed_pos >= spe:
        train_loader.sampler.discard_pending()
        resumed_pos = 0
    # byte totals are plain scalars: the accountant's per-round rows
    # are COHORT-indexed since ISSUE 9 — a per-population accumulator
    # here was an O(num_clients) host allocation per epoch
    total_down = 0.0
    total_up = 0.0

    writer = None
    if cfg.use_tensorboard and mh.is_coordinator():
        writer = _try_tensorboard(log_dir)

    profiling = False
    profiled = False
    while rounds_done < total_rounds:
        epoch += 1
        if cfg.do_profile and not profiled:
            # device-level trace of the first trained epoch (compile +
            # steady-state rounds), viewable in TensorBoard/Perfetto
            jax.profiler.start_trace(
                os.path.join(log_dir or ".", "profile"))
            profiling = profiled = True
        epoch_rounds = min(spe - resumed_pos,
                           total_rounds - rounds_done)
        if model.scheduler is not None:
            # sync the scheduler's round counter to the stream about
            # to be drawn: the resumed first epoch replays (and
            # re-selects, without dispatching) its skipped head, so
            # the counter starts at the EPOCH's first round
            model.scheduler.begin_epoch(rounds_done - skip_rounds)
        epoch_stream = train_loader.epoch(skip=skip_rounds)
        skip_rounds = 0
        resumed_pos = 0
        losses, accs = [], []
        down = 0.0
        up = 0.0

        # EMNIST prints one line per STEP (reference cv_train.py:233-237)
        per_step_log = (cfg.dataset_name == "EMNIST"
                        and mh.is_coordinator())
        step_t0 = [_now()]
        # scan mode has no per-round boundaries — rounds of a span all
        # emit at flush — so Time is the span-amortized per-round value
        # (set by on_flush); the unscanned path measures each step
        amortized = [0.0]

        def step_line(lr, elapsed):
            print("LR: {:0.5f}, Loss: {:0.5f}, Acc: {:0.5f}, "
                  "Time: {:0.2f}".format(float(lr), losses[-1], accs[-1],
                                         elapsed))

        if cfg.scan_rounds:
            # scanned device programs, flushed every --scan_span rounds
            # to bound the staged [N, W, B, ...] arrays (0 = whole
            # epoch); staging/flush mechanics shared with gpt2_train
            # (training/scanloop.py)
            taken = 0


            def stream():
                # cap-BEFORE-pull: the epoch budget is checked before
                # drawing the next round, so ending an epoch never
                # draws-and-discards a round (a phantom rng advance no
                # resume could reproduce), and the abandonment mark
                # lands before any checkpoint that follows — a resume
                # from the epoch's last span checkpoint (pos == cap)
                # discards the restored stream exactly where this run
                # abandons it
                nonlocal taken
                stream_it = iter(epoch_stream)
                while taken < epoch_rounds:
                    try:
                        client_ids, data, mask = next(stream_it)
                    except StopIteration:
                        return
                    lr_scheduler.step()
                    taken += 1
                    lr = opt.param_groups[0]["lr"]
                    yield (lr, client_ids, data, mask, lr)
                train_loader.sampler.abandon_epoch()

            def on_flush(n_rounds):
                amortized[0] = (_now() - step_t0[0]) / max(n_rounds, 1)
                step_t0[0] = _now()

            def scan_emit(lr, loss_w, acc_w):
                losses.append(float(np.mean(loss_w)))
                accs.append(float(np.mean(acc_w)))
                if per_step_log:
                    step_line(lr, amortized[0])
                return True  # NaN abort handled by the epoch-mean check

            def on_comm(d, u):
                nonlocal down, up
                down += float(np.sum(d))
                up += float(np.sum(u))

            run_scanned_rounds(
                model, stream(),
                # palette mode hands the controller bank in as the
                # adaptive span provider; static --scan_span otherwise
                model.control_bank if cfg.span_palette
                else (cfg.scan_span if cfg.scan_span > 0
                      else epoch_rounds),
                scan_emit, on_comm, on_flush=on_flush,
                # span-boundary saves bound a mid-span preemption's
                # loss to ckpt_every_spans spans, not one epoch
                checkpoint=make_span_checkpoint(
                    _ckpt_path(cfg), model, cfg, lr_scheduler),
                guard=guard,
                # --pipeline: double-buffered dispatch — span t+1
                # stages/dispatches while span t runs on device and
                # span t-1 persists (ISSUE 10)
                pipeline=cfg.pipeline)
            rounds_done += taken
        else:
            # metrics materialize with a ONE-ROUND lag: float()ing the
            # round just dispatched would block the host on the device
            # every round (a full tunnel round-trip — PERF.md); round
            # t-1's values are already computed, so float() is free.
            # NaN abort latency grows by exactly one round.
            def emit(p) -> bool:
                # gather_host: per-client metrics are cross-process
                # sharded in multi-controller runs (np.asarray in
                # single-process ones)
                losses.append(float(np.mean(mh.gather_host(p[0]))))
                accs.append(float(np.mean(mh.gather_host(p[1]))))
                if per_step_log:
                    step_line(p[2], _now() - step_t0[0])
                    step_t0[0] = _now()
                return not np.isnan(losses[-1])

            pending = None
            stream_it = iter(epoch_stream)
            while True:
                if rounds_done >= total_rounds:
                    # round budget reached mid-stream: abandon
                    # WITHOUT pulling (see the scanned cap above) so
                    # any later checkpoint records in_epoch=0
                    train_loader.sampler.abandon_epoch()
                    break
                try:
                    client_ids, data, mask = next(stream_it)
                except StopIteration:
                    break
                lr_scheduler.step()
                # first dispatch of the process compiles; every later
                # one is steady state and runs under the (optional)
                # transfer guard — same warmup exemption as the
                # scanned path
                ctx = (guard() if guard is not None and warmed[0]
                       else contextlib.nullcontext())
                with ctx:
                    loss, acc, d, u = model((client_ids, data, mask))
                warmed[0] = True
                opt.step()
                down += float(np.sum(d))
                up += float(np.sum(u))
                if pending is not None and not emit(pending):
                    pending = None
                    break
                pending = (loss, acc, opt.param_groups[0]["lr"])
                rounds_done += 1
            if pending is not None:
                emit(pending)

        total_down += down
        total_up += up
        if profiling:
            jax.profiler.stop_trace()
            profiling = False
            print(f"profile trace written to "
                  f"{os.path.join(log_dir or '.', 'profile')}")
        train_time = timer()

        mean_loss = float(np.mean(losses)) if losses else float("nan")
        mean_acc = float(np.mean(accs)) if accs else float("nan")

        # NaN abort (reference cv_train.py:110-112,222-224); every
        # controller computes the same mean, so all abort together
        if np.isnan(mean_loss) or mean_loss > cfg.nan_threshold:
            if mh.is_coordinator():
                print(f"found nan/divergent loss {mean_loss}, aborting")
            return False

        val_loss, val_acc = run_eval(model, val_loader)
        val_time = timer()

        row = {
            "epoch": epoch,
            "lr": round(float(opt.param_groups[0]["lr"]), 5),
            "train_time": train_time,
            "train_loss": mean_loss,
            "train_acc": mean_acc,
            "test_time": val_time,
            "test_loss": val_loss,
            "test_acc": val_acc,
            "down (MiB)": float(total_down / (1024 ** 2)),
            "up (MiB)": float(total_up / (1024 ** 2)),
            "total_time": timer.total_time,
        }
        for logger in loggers:
            logger.append(row)
        if writer is not None:
            for name, value in row.items():
                if name != "epoch":
                    writer.add_scalar(name.split(" ")[0], value, epoch)
        if model.telemetry is not None:
            # drain the one-round-lag metric buffer, then journal the
            # same summary row the stdout table shows
            model.telemetry.flush()
            model.telemetry.journal_event(
                "epoch", **{k.replace(" (MiB)", "_mib"): v
                            for k, v in row.items()})
            # one full epoch compiled everything a steady-state run
            # needs (train round + eval); later compiles are retraces
            # and journal as compile_warning
            model.telemetry.mark_steady_state()

        if cfg.checkpoint_every and epoch % cfg.checkpoint_every == 0:
            # atomic rotated save: keep-last-k round-stamped files + a
            # `latest` manifest, so a preemption at ANY instant leaves
            # a loadable checkpoint for --resume (utils/checkpoint)
            import time
            t0 = time.monotonic()  # monotonic like the sibling sites
            # queued span-boundary writes (--pipeline) must land
            # before this synchronous save rotates the manifest
            model.drain_persistence()
            with TRACE.span("checkpoint", round=int(rounds_done)):
                path = save_rotating(
                    _ckpt_path(cfg), model.server, model.clients,
                    keep_last=cfg.keep_checkpoints,
                    max_age_hours=cfg.ckpt_max_age_hours,
                    scheduler_step=lr_scheduler.step_count,
                    accountant=model.accountant,
                    prev_change_words=model._prev_change_words,
                    fingerprint=model.checkpoint_fingerprint,
                    throughput=model.throughput.state_dict(),
                    scheduler=model.scheduler_state(),
                    sampler=model.sampler_state(),
                    async_admit=model.async_admit_state(),
                    client_rows=model.client_rows_payload())
            if model.telemetry is not None:
                model.telemetry.journal_event(
                    "checkpoint", path=path,
                    seconds=round(time.monotonic() - t0, 3))
            if mh.is_coordinator():
                print(f"checkpointed to {path}")

    return True


def _now() -> float:
    # monotonic, not wall clock: every consumer subtracts two _now()
    # values to form a duration (step timing), and a wall-clock delta
    # is not a duration — an NTP step mid-epoch would print negative
    # or wildly wrong step times (graftlint GL011)
    import time
    return time.monotonic()


def _try_tensorboard(log_dir):
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(log_dir=log_dir)
    # broad by necessity: tensorboard/protobuf version skew raises
    # AttributeError/TypeError, not just ImportError, and no fault-
    # harness code can run inside an import — InjectedFault cannot
    # originate here
    except Exception as e:  # graftlint: disable=GL005 -- optional-dep probe
        print(f"tensorboard unavailable ({e}); continuing without")
        return None


def _ckpt_path(cfg: Config) -> str:
    return os.path.join(cfg.checkpoint_path, cfg.model)


# ---------------- main (reference cv_train.py:289-421) -------------------

def main(argv=None) -> bool:
    enable_persistent_compilation_cache()
    cfg = parse_args(argv=argv)
    if cfg.multihost:
        # must precede every backend touch (jax.device_count below)
        mh.initialize_from_config(cfg)
    if mh.is_coordinator():
        print(cfg)
    timer = Timer()
    np.random.seed(cfg.seed)

    # --test smoke shrink (reference cv_train.py:329-336)
    model_config = {}
    if cfg.do_test:
        model_config["channels"] = {"prep": 1, "layer1": 1,
                                    "layer2": 1, "layer3": 1}
        cfg = cfg.replace(num_cols=10, num_rows=1, k=10)
    if cfg.do_finetune:
        assert cfg.finetuned_from is not None, \
            "--finetuned_from required with --finetune"
    model_config.update(num_classes=num_classes_of_dataset(cfg.dataset_name),
                        do_batchnorm=cfg.do_batchnorm)

    train_loader, val_loader = get_data_loaders(cfg)

    # derive the model's input shape from the actual (transformed)
    # data — 32x32x3 CIFAR, 28x28x1 EMNIST, 224x224x3 ImageNet all
    # route through here (the reference hardwires per-dataset
    # model_config at cv_train.py:345-358)
    x0 = train_loader.dataset.get_client_batch(0, np.array([0]))[0]
    model_config["initial_channels"] = int(x0.shape[-1])
    module = models.build_model(cfg.model, **model_config)
    init_x = jnp.zeros((2,) + x0.shape[1:], jnp.float32)
    params = module.init(jax.random.PRNGKey(cfg.seed), init_x)

    # finetune: transfer the old body, keep the fresh head, and freeze
    # the transferred leaves by zeroing their per-parameter LR
    # (reference freezes with requires_grad=False + head-only param
    # groups, cv_train.py:377-384)
    lr_scale_vec = None
    if cfg.do_finetune:
        # resolve like --resume does (manifest -> stamped -> fixed
        # name): a preempted pretrain run leaves only rotated
        # checkpoints, and its newest state is still finetunable
        src = latest_checkpoint_path(
            os.path.join(cfg.finetune_path, cfg.model))
        if src is None:
            raise FileNotFoundError(
                f"no checkpoint for model {cfg.model!r} under "
                f"--finetune_path {cfg.finetune_path!r}")
        old_server = load_checkpoint(src).server
        # rebuild the OLD model's param template to unflatten into
        old_cfg_classes = num_classes_of_dataset(cfg.finetuned_from)
        old_module = models.build_model(
            cfg.model, **{**model_config, "num_classes": old_cfg_classes})
        old_params = old_module.init(jax.random.PRNGKey(cfg.seed), init_x)
        from commefficient_tpu.ops.flat import flatten_params
        _, old_unravel = flatten_params(old_params)
        params, frozen_mask = transfer_for_finetune(
            old_unravel(old_server.ps_weights), params)
        lr_scale_vec = _mask_to_lr_scales(params, frozen_mask)

    # Fixup nets: biases and scalar scales train at 0.1x LR via a
    # per-parameter scale vector (reference cv_train.py:366-376 builds
    # param groups with lr 0.1/0.1/1)
    if cfg.model.startswith("Fixup"):
        if mh.is_coordinator():
            print("using fixup learning rates")
        lr_scale_vec = _fixup_lr_scales(params)

    compute_loss = make_compute_loss(module)
    model = FedModel(None, compute_loss, cfg, params=params,
                     num_clients=train_loader.dataset.num_clients,
                     lr_scale_vec=lr_scale_vec)
    opt = FedOptimizer(model)

    # round scheduler (commefficient_tpu/scheduler): policy-driven
    # participant sampling + deadline-driven rounds over the model's
    # own throughput tracker. Attached BEFORE --resume so a
    # checkpoint's sched_* counters restore into this instance; the
    # uniform/no-deadline default is bit-identical to a scheduler-free
    # build.
    from commefficient_tpu.scheduler import attach_round_scheduler
    attach_round_scheduler(model, train_loader)

    # coordinator-broadcast control plane (ISSUE 12): attach the
    # configured plan transport — "collective" wires the production
    # one-to-all host broadcast onto the scheduler above, "emulated"
    # replaces it with the in-process N-controller harness (the CI
    # fault surface). Attached BEFORE --resume like the scheduler, so
    # restored sched_* counters land in every controller replica.
    from commefficient_tpu.parallel.plantransport import (
        attach_config_transport,
    )
    attach_config_transport(model, train_loader, cfg)

    if mh.is_multihost():
        # per-process batch feeding — or, on non-contiguous layouts,
        # the globalize() fallback (one shared implementation:
        # multihost.apply_feed_slices)
        mh.apply_feed_slices(model, train_loader, val_loader,
                             cfg.num_workers, val_loader.num_shards)

    sched_step = 0
    ckpt_fallbacks = []
    if cfg.resume:
        # auto-resume-from-latest, corruption-tolerant (ISSUE 12
        # satellite): integrity-check the newest rotated checkpoint
        # against the manifest's per-array checksums and FALL BACK to
        # the previous keep-last-k rotation when it is corrupt or
        # truncated, instead of crashing mid-resume; each skipped file
        # is journaled as a loud `checkpoint_fallback` event once the
        # telemetry session exists. Fingerprint-validated so a wrong
        # checkpoint dir still fails with the offending field named.
        loaded = load_resilient(
            _ckpt_path(cfg),
            expect_fingerprint=model.checkpoint_fingerprint,
            on_fallback=lambda p, why: ckpt_fallbacks.append((p, why)))
        if loaded is not None:
            ck_file, ckpt = loaded
            sched_step = model.load_state(ckpt)
            if mh.is_coordinator():
                print(f"resumed from {ck_file} at round "
                      f"{int(ckpt.server.round_idx)}")
        if model.plan_transport is not None and cfg.journal_path:
            # deterministic restart (ISSUE 12): load the pre-crash
            # run's write-ahead plan stream — replayed rounds must
            # recompute the identical install digests, or the resume
            # fails loud instead of silently rewriting history
            model.load_plan_stream(cfg.journal_path)

    # LR schedule (reference cv_train.py:392-404; cifar10-fast default
    # knots [0, pivot, num_epochs] -> [0, lr_scale, 0])
    lr_scale = cfg.lr_scale if cfg.lr_scale is not None else 0.4
    schedule = PiecewiseLinear([0, cfg.pivot_epoch, cfg.num_epochs],
                               [0, lr_scale, 0])
    spe = train_loader.steps_per_epoch
    lr_scheduler = LambdaLR(opt, lr_lambda=lambda step: schedule(step / spe))
    lr_scheduler.load_state_dict({"step_count": sched_step})

    coord = mh.is_coordinator()
    # only the coordinator creates a run dir
    log_dir = make_logdir(cfg) if coord else ""
    from commefficient_tpu.telemetry import attach_run_telemetry
    tele = attach_run_telemetry(model, cfg, log_dir, coord,
                                driver="cv_train",
                                materialize=mh.gather_host)
    if tele is not None:
        # resume-time integrity fallbacks, journaled now that the
        # session exists (the resume ran before telemetry attach)
        for p, why in ckpt_fallbacks:
            tele.journal_event("checkpoint_fallback", path=p,
                               error=why[:200])
    if coord:
        print(f"Finished initializing in {timer():.2f} seconds")

    ok = False
    try:
        from commefficient_tpu.telemetry import NumericTripError
        trips = 0
        while True:
            try:
                ok = train(model, opt, lr_scheduler, train_loader,
                           val_loader, cfg,
                           loggers=(TableLogger(),) if coord else (),
                           timer=timer, log_dir=log_dir)
                break
            except NumericTripError as trip:
                # finite-frontier auto-rollback (ISSUE 16): the trip
                # is already journaled durable; walk back to the
                # newest finite checkpoint and replay with screening
                # forced on. Bounded — exhausting the budget (or
                # having no finite checkpoint) fails loud.
                trips += 1
                if trips > cfg.max_numeric_rollbacks:
                    raise
                sched_step = numeric_rollback(
                    model, _ckpt_path(cfg), cfg, tele, trip)
                if sched_step is None:
                    raise
                lr_scheduler.load_state_dict(
                    {"step_count": sched_step})
        model.finalize()

        if cfg.do_checkpoint:
            # collective (gathers sharded client state); coordinator
            # writes stamped + manifest (what --resume prefers) AND the
            # fixed-name artifact the finetune path loads, in one gather
            model.drain_persistence()
            path = save_final(
                _ckpt_path(cfg), model.server, model.clients,
                keep_last=cfg.keep_checkpoints,
                max_age_hours=cfg.ckpt_max_age_hours,
                scheduler_step=lr_scheduler.step_count,
                accountant=model.accountant,
                prev_change_words=model._prev_change_words,
                fingerprint=model.checkpoint_fingerprint,
                throughput=model.throughput.state_dict(),
                scheduler=model.scheduler_state(),
                sampler=model.sampler_state(),
                async_admit=model.async_admit_state(),
                client_rows=model.client_rows_payload())
            if coord:
                print(f"saved checkpoint to {path}")
    finally:
        # close even when training raises (an InjectedFault drill, a
        # NaN abort, a real crash): the session must detach its global
        # compile listener and stop any live profiler capture, or the
        # next in-process run inherits both. The persistence writer
        # drains FIRST (--pipeline): a queued span checkpoint flushes
        # at a crash exactly like at a clean shutdown.
        try:
            model.close_persistence()
        finally:
            if tele is not None:
                tele.close(ok=bool(ok))
    return ok




def _mask_to_lr_scales(params, frozen_mask) -> np.ndarray:
    """Flat per-parameter LR-scale vector: 0.0 where frozen_mask marks
    a leaf as transferred/frozen, 1.0 elsewhere."""
    import jax.tree_util as jtu

    segs = []
    for leaf, frozen in zip(jtu.tree_leaves(params),
                            jtu.tree_leaves(frozen_mask)):
        scale = 0.0 if float(frozen) else 1.0
        segs.append(np.full(int(np.prod(leaf.shape)), scale, np.float32))
    return np.concatenate(segs)


def _fixup_lr_scales(params) -> np.ndarray:
    """Flat per-parameter LR-scale vector: 0.1 for bias/scale scalars,
    1.0 elsewhere (reference param groups, cv_train.py:366-376)."""
    import jax.tree_util as jtu

    leaves = jtu.tree_flatten_with_path(params)[0]
    segs = []
    for path, leaf in leaves:
        names = "/".join(str(p) for p in path).lower()
        scale = 0.1 if ("bias" in names or "scale" in names
                        or "mul" in names or "add" in names) else 1.0
        segs.append(np.full(int(np.prod(leaf.shape)), scale, np.float32))
    return np.concatenate(segs)


def cli() -> None:
    """Console entry point (`cv-train`, pyproject.toml)."""
    raise SystemExit(0 if main() else 1)


if __name__ == "__main__":
    cli()

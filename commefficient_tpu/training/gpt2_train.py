"""GPT2 / PersonaChat federated training driver.

The reference driver's launch surface re-created on the TPU runtime
(reference: CommEfficient/gpt2_train.py — double-heads loss callbacks
:77-99, special-token handling :101-112, per-batch-logging train loop
`run_batches` :169-253, val NLL/accuracy/perplexity :242-253, main
wiring :255-313): same flags (config.parse_args, default lr 4e-2 at
:256), same loss-callback contract, same epoch-1-only download
reporting (:132-137). The federated core underneath is the identical
workload-agnostic round engine cv_train uses — preserving the
reference's key API contract (SURVEY.md §3.5).

Run: python -m commefficient_tpu.training.gpt2_train --dataset_name
PERSONA --mode sketch --error_type virtual ...
"""
from __future__ import annotations

import contextlib
import math
import os
import time
from typing import Optional

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config, parse_args
from commefficient_tpu.data.loader import FedLoader, FedValLoader
from commefficient_tpu.data.persona import (
    FedPERSONA, IGNORE_INDEX, make_tokenizer,
)
from commefficient_tpu.federated.api import FedModel, FedOptimizer
from commefficient_tpu.models.gpt2 import (
    GPT2Config, GPT2DoubleHeads, PRESETS, build_gpt2, load_pretrained_dir,
    resize_position_embeddings, resize_token_embeddings, save_pretrained,
    try_load_pretrained,
)
from commefficient_tpu.parallel import multihost as mh
from commefficient_tpu.parallel.mesh import make_multihost_client_mesh
from commefficient_tpu.parallel.tp import tp_loss
from commefficient_tpu.telemetry.trace import TRACE
from commefficient_tpu.training.scanloop import (
    make_span_checkpoint, numeric_rollback, run_scanned_rounds,
)
from commefficient_tpu.utils.cache import enable_persistent_compilation_cache
from commefficient_tpu.utils.checkpoint import (
    save_checkpoint, save_final, save_rotating,
)
from commefficient_tpu.utils.logging import (
    NullLogger, TableLogger, Timer, make_logdir,
)
from commefficient_tpu.utils.schedules import LambdaLR, PiecewiseLinear


# ---------------- loss callbacks (reference gpt2_train.py:77-99) ---------

def _lm_nll(lm_logits, lm_labels, mask):
    """Shifted next-token NLL over non-ignored labels of valid
    examples (reference inference() shift at gpt2_train.py:63-68 +
    CrossEntropyLoss(ignore_index=-1) at :78)."""
    logits = lm_logits[..., :-1, :]
    labels = lm_labels[..., 1:]
    valid = ((labels != IGNORE_INDEX)
             * mask[:, None, None]).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return (nll * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def _mc_loss_acc(mc_logits, mc_labels, mask):
    """Candidate-choice cross-entropy + accuracy (the double head)."""
    logp = jax.nn.log_softmax(mc_logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, mc_labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    acc = ((mc_logits.argmax(-1) == mc_labels) * mask).sum() / denom
    return loss, acc


def make_compute_loss_train(model: GPT2DoubleHeads, cfg: Config):
    def compute_loss(params, batch, mask):
        input_ids, mc_token_ids, lm_labels, mc_labels, token_type_ids = batch
        lm_logits, mc_logits = model.apply(
            params, input_ids, token_type_ids, mc_token_ids)
        lm = _lm_nll(lm_logits, lm_labels, mask)
        mc, _ = _mc_loss_acc(mc_logits, mc_labels, mask)
        loss = lm * cfg.lm_coef + mc * cfg.mc_coef
        return loss, (lm, mc)
    return compute_loss


def make_compute_loss_val(model: GPT2DoubleHeads):
    """Val = (NLL, (accuracy,)); perplexity is exp(mean NLL), computed
    by the caller over the whole val set (reference gpt2_train.py:253)."""
    def compute_loss(params, batch, mask):
        input_ids, mc_token_ids, lm_labels, mc_labels, token_type_ids = batch
        lm_logits, mc_logits = model.apply(
            params, input_ids, token_type_ids, mc_token_ids)
        nll = _lm_nll(lm_logits, lm_labels, mask)
        _, acc = _mc_loss_acc(mc_logits, mc_labels, mask)
        return nll, (acc,)
    return compute_loss


# ---------------- data (reference gpt2_train.py:315-355) -----------------

def get_data_loaders(cfg: Config, tokenizer):
    synthetic = (8, 2, 3) if cfg.do_test else None
    common = dict(dataset_dir=cfg.dataset_dir, tokenizer=tokenizer,
                  num_candidates=cfg.num_candidates,
                  max_history=cfg.max_history, do_iid=cfg.do_iid,
                  seed=cfg.seed, synthetic_examples=synthetic)
    train_set = FedPERSONA(
        personality_permutations=cfg.personality_permutations,
        num_clients=cfg.num_clients, train=True, **common)
    val_set = FedPERSONA(
        personality_permutations=cfg.personality_permutations,
        train=False, **common)
    train_loader = FedLoader(train_set, cfg.num_workers,
                             cfg.local_batch_size, seed=cfg.seed,
                             max_local_batch=cfg.max_local_batch)
    val_loader = FedValLoader(val_set, cfg.valid_batch_size,
                              num_shards=min(jax.device_count(),
                                             cfg.num_workers))
    return train_loader, val_loader


# ---------------- eval (reference test_gpt2, gpt2_train.py:149-167) ------

def run_eval(model: FedModel, val_loader):
    model.train(False)
    tot_nll = tot_acc = tot_n = 0.0
    for data, mask in val_loader.batches():
        nll, acc, count = model((data, mask))
        tot_nll += float((nll * count).sum())
        tot_acc += float((acc * count).sum())
        tot_n += float(count.sum())
    model.train(True)
    denom = max(tot_n, 1.0)
    nll = tot_nll / denom
    return nll, tot_acc / denom, float(np.exp(min(nll, 50.0)))


# ---------------- training loop (reference run_batches, :169-253) --------

def train_gpt2(model: FedModel, opt: FedOptimizer, lr_scheduler,
               train_loader, cfg: Config,
               logger=None, timer: Optional[Timer] = None,
               log_dir: str = ""):
    timer = timer or Timer()
    logger = logger or TableLogger()
    spe = train_loader.steps_per_epoch
    epoch_download = epoch_upload = 0.0
    # --debug_transfer_guard: implicit host<->device transfers raise in
    # the steady-state loop (every dispatch after the compiling first
    # one) — same wiring as cv_train.train
    guard = None
    if cfg.debug_transfer_guard:
        from commefficient_tpu.analysis.runtime import forbid_transfers
        guard = forbid_transfers
    warmed = [False]
    # on resume, num_epochs is the TOTAL budget: rounds already done
    # (restored round_idx) count against it — same contract as
    # cv_train.train (cv_train.py:136-140); without this the resumed
    # run replays the whole budget and the lr schedule's final knot is
    # exceeded (np.interp clamps lr to 0)
    batch_idx = int(model.server.round_idx)
    start_epoch = batch_idx // spe
    # mid-epoch resume: fast-forward the first resumed epoch's loader
    # stream past the rounds already trained, so the epoch's early
    # batches aren't re-trained while batch_idx continues mid-epoch
    # (data coverage matches an uninterrupted run up to the sampler's
    # fresh permutation; LR schedule and budget were already correct).
    # With checkpointed sampler state (smp_* keys) resolve_resume
    # collapses the skip to 0 and the restored cursor continues the
    # exact stream — same contract as cv_train.train.
    skip_rounds = train_loader.sampler.resolve_resume(
        batch_idx % spe)
    # a stream restored AT the per-epoch cap was abandoned right
    # there by the uninterrupted run — discard it so the resumed
    # epoch draws fresh (cv_train applies the same rule; here the
    # absolute batch_idx cap already bounds the remainder, so no
    # budget subtraction is needed)
    if (train_loader.sampler.pending_pos or 0) >= spe:
        train_loader.sampler.discard_pending()
    ckpt_path = os.path.join(cfg.checkpoint_path, "gpt2")

    if cfg.do_profile:
        jax.profiler.start_trace(os.path.join(log_dir or ".", "profile"))
    for epoch in range(start_epoch, math.ceil(cfg.num_epochs)):
        frac = (cfg.num_epochs - epoch
                if epoch == math.ceil(cfg.num_epochs) - 1 else 1.0)
        losses = []

        # per-batch metrics are logged with a ONE-ROUND lag: round t-1
        # is already computed when round t dispatches, so float() costs
        # nothing; float()ing the fresh round would block the host
        # every round (PERF.md). NaN abort latency grows by one round.
        def emit(p) -> bool:
            bidx, lr_v, l_, lm_, mc_ = p
            # gather_host: metrics are cross-process sharded in
            # multi-controller runs (np.asarray otherwise)
            l_, lm_, mc_ = (mh.gather_host(l_), mh.gather_host(lm_),
                            mh.gather_host(mc_))
            losses.append(float(np.mean(l_)))
            logger.append({
                "batch_idx": bidx,
                "lr": round(lr_v, 5),
                "train_time": timer(),
                "train_loss": losses[-1],
                "lm_loss": float(np.mean(lm_)),
                "mc_loss": float(np.mean(mc_)),
                "total_time": timer.total_time,
            })
            return not (np.isnan(losses[-1])
                        or losses[-1] > cfg.nan_threshold)

        pending = None
        aborted = False
        if model.scheduler is not None:
            # sync the scheduler's round counter to the epoch stream
            # (resume replays the skipped head — same as cv_train)
            model.scheduler.begin_epoch(batch_idx - skip_rounds)
        # sampler-level skip: the skipped rounds advance index math
        # only, never materializing batch data (O(skip) host work was
        # O(skip × batch fetch+transform) before)
        epoch_stream = train_loader.epoch(skip=skip_rounds)
        skip_rounds = 0
        if cfg.scan_rounds:
            # scanned device programs, flushed every --scan_span rounds
            # (symmetric with cv_train; bounds the staged token arrays)
            def stream():
                # cap-BEFORE-pull: never draw-and-discard a round at
                # the epoch cap, and mark the abandonment before any
                # checkpoint that follows (same contract as
                # cv_train's scanned stream)
                nonlocal batch_idx
                stream_it = iter(epoch_stream)
                while batch_idx - epoch * spe < spe * frac:
                    try:
                        client_ids, data, mask = next(stream_it)
                    except StopIteration:
                        return
                    lr_scheduler.step()
                    batch_idx += 1
                    lr_v = opt.param_groups[0]["lr"]
                    yield ((batch_idx, float(lr_v)), client_ids, data,
                           mask, lr_v)
                train_loader.sampler.abandon_epoch()

            def on_comm(d, u):
                nonlocal epoch_download, epoch_upload
                if epoch == 0:
                    epoch_download += d.sum() / (1024 ** 2)
                    epoch_upload += u.sum() / (1024 ** 2)

            aborted = not run_scanned_rounds(
                model, stream(),
                # palette mode hands the controller bank in as the
                # adaptive span provider; static --scan_span otherwise
                model.control_bank if cfg.span_palette
                else (cfg.scan_span if cfg.scan_span > 0 else spe),
                lambda tag, l_, lm_, mc_: emit(
                    (tag[0], tag[1], l_, lm_, mc_)),
                on_comm,
                # span-boundary saves bound a mid-span preemption's
                # loss to ckpt_every_spans spans, not one epoch
                checkpoint=make_span_checkpoint(
                    ckpt_path, model, cfg, lr_scheduler),
                guard=guard,
                # --pipeline: double-buffered dispatch (ISSUE 10)
                pipeline=cfg.pipeline)
        else:
            stream_it = iter(epoch_stream)
            while True:
                if batch_idx - epoch * spe >= spe * frac:
                    # epoch cap: abandon WITHOUT pulling — the epoch-
                    # cadence checkpoint below must record in_epoch=0
                    # and no phantom draw may advance the rng
                    train_loader.sampler.abandon_epoch()
                    break
                try:
                    client_ids, data, mask = next(stream_it)
                except StopIteration:
                    break
                lr_scheduler.step()
                ctx = (guard() if guard is not None and warmed[0]
                       else contextlib.nullcontext())
                with ctx:
                    loss, lm, mc, down, up = model(
                        (client_ids, data, mask))
                warmed[0] = True
                opt.step()
                batch_idx += 1
                if epoch == 0:
                    # download deltas are only trusted for epoch 1
                    # (reference gpt2_train.py:132-137)
                    epoch_download += down.sum() / (1024 ** 2)
                    epoch_upload += up.sum() / (1024 ** 2)
                if pending is not None and not emit(pending):
                    pending = None
                    aborted = True
                    break
                pending = (batch_idx, float(opt.param_groups[0]["lr"]),
                           loss, lm, mc)
            if pending is not None and not emit(pending):
                aborted = True
        if aborted:
            if mh.is_coordinator():
                print(f"found nan/divergent loss {losses[-1]}, aborting")
            if cfg.do_profile and epoch == start_epoch:
                jax.profiler.stop_trace()
            return False
        if cfg.do_profile and epoch == start_epoch:
            jax.profiler.stop_trace()
            print(f"profile trace written to "
                  f"{os.path.join(log_dir or '.', 'profile')}")
        # mid-run checkpoint so --resume has something to pick up when
        # the run is killed (symmetric with cv_train.py's per-epoch
        # save; the resume-read half alone would be unreachable)
        if model.telemetry is not None:
            # drain the one-round-lag metric buffer + journal an epoch
            # summary (symmetric with cv_train.train); after one full
            # epoch the train programs are compiled — later train-loop
            # compiles journal as compile_warning (the final eval runs
            # under expect_compiles, see main)
            model.telemetry.flush()
            model.telemetry.journal_event(
                "epoch", epoch=epoch,
                train_loss=(losses[-1] if losses else None),
                rounds=batch_idx)
            model.telemetry.mark_steady_state()
        if cfg.checkpoint_every and epoch % cfg.checkpoint_every == 0:
            # atomic rotated save (keep-last-k + `latest` manifest) —
            # the preemption-safe half of --resume (utils/checkpoint)
            t0 = time.monotonic()
            # queued span-boundary writes (--pipeline) must land
            # before this synchronous save rotates the manifest
            model.drain_persistence()
            with TRACE.span("checkpoint",
                            round=int(getattr(model, "_rounds_done",
                                              0))):
                written = save_rotating(
                    ckpt_path, model.server, model.clients,
                    keep_last=cfg.keep_checkpoints,
                    max_age_hours=cfg.ckpt_max_age_hours,
                    scheduler_step=lr_scheduler.step_count,
                    accountant=model.accountant,
                    prev_change_words=model._prev_change_words,
                    fingerprint=model.checkpoint_fingerprint,
                    throughput=model.throughput.state_dict(),
                    scheduler=model.scheduler_state(),
                    sampler=model.sampler_state(),
                    async_admit=model.async_admit_state(),
                    client_rows=model.client_rows_payload())
            if model.telemetry is not None:
                model.telemetry.journal_event(
                    "checkpoint", path=written,
                    seconds=round(time.monotonic() - t0, 3))
            if mh.is_coordinator():
                print(f"checkpointed to {written}")

    n_clients = model.num_clients
    if mh.is_coordinator():
        print(f"Total Download (MiB): {epoch_download:0.2f} (only epoch 1)")
        print(f"Total Upload (MiB): {epoch_upload:0.2f} (only epoch 1)")
        print(f"Avg Download Per Client: {epoch_download / n_clients:0.2f}"
              f" (only epoch 1)")
        print(f"Avg Upload Per Client: {epoch_upload / n_clients:0.2f}"
              f" (only epoch 1)")
    return True


def test_gpt2(model: FedModel, val_loader, timer: Optional[Timer] = None,
              logger=None):
    timer = timer or Timer()
    nll, acc, ppl = run_eval(model, val_loader)
    stats = {"val_nll": nll, "val_acc": acc, "val_ppl": ppl,
             "val_time": timer(), "total_time": timer.total_time}
    (logger or TableLogger()).append(stats)
    return stats


# ---------------- main (reference train(), gpt2_train.py:255-313) --------

def build_model_and_params(cfg: Config, tokenizer, seq_len: int,
                           source: Optional[str] = None,
                           require_load: bool = False):
    """Build the Flax GPT2 sized for the tokenizer + corpus; import
    weights from `source` — a save_pretrained artifact directory (the
    --finetune path), a local HF checkpoint, or a preset name — with
    random init as the fallback. require_load=True turns the fallback
    into an error (the --finetune contract: evaluating a fresh init as
    if it were the finetuned model would silently report garbage; the
    reference fails inside from_pretrained the same way)."""
    vocab = len(tokenizer)
    key = jax.random.PRNGKey(cfg.seed)
    source = source or cfg.model_checkpoint

    loaded = load_pretrained_dir(source, key=key)
    if loaded is not None:
        # our own HF-style artifact: config rides along, any scale
        # (incl. the tiny --test model a smoke run saved). Widen the
        # position table if this corpus pads longer than the artifact's
        # (same hazard the other branches handle via max(., seq_len))
        pretrained, gcfg = loaded
        if seq_len > gcfg.n_positions:
            pretrained = resize_position_embeddings(
                pretrained, seq_len, key=key,
                initializer_range=gcfg.initializer_range)
            gcfg = gcfg.replace(n_positions=seq_len)
    elif require_load:
        # finetune_path may also name a stock HF checkpoint directory
        # (the reference hands it straight to from_pretrained)
        gcfg = PRESETS["gpt2"].replace(
            n_positions=max(PRESETS["gpt2"].n_positions, seq_len))
        pretrained = try_load_pretrained(source, gcfg, key=key)
        if pretrained is None:
            raise FileNotFoundError(
                f"--finetune: no loadable artifact at {source!r} "
                "(expected config.json + pytorch_model.bin/.npz from a "
                "previous run's save_pretrained, or a local HF "
                "checkpoint)")
    elif cfg.do_test:
        gcfg = GPT2Config(vocab_size=vocab, n_positions=max(seq_len, 8),
                          n_embd=32, n_layer=2, n_head=2)
        pretrained = None
    else:
        base = PRESETS.get(source, PRESETS["gpt2"])
        gcfg = base.replace(n_positions=max(base.n_positions, seq_len))
        pretrained = try_load_pretrained(source, gcfg, key=key)
        if pretrained is None:
            # from-scratch: size the embedding directly for the
            # tokenizer (no resize step needed)
            gcfg = gcfg.replace(vocab_size=vocab)

    # remat is an execution-layout choice, not part of the artifact:
    # apply the flag regardless of where the config came from
    gcfg = gcfg.replace(remat=cfg.do_remat)

    if pretrained is not None:
        params = pretrained
        if vocab > gcfg.vocab_size:
            # special-token embedding resize (reference :101-112);
            # the module is rebuilt at the grown vocab to match
            params = resize_token_embeddings(params, vocab, key=key)
            gcfg = gcfg.replace(vocab_size=vocab)
        module = GPT2DoubleHeads(gcfg)
    else:
        module = GPT2DoubleHeads(gcfg)
        C = max(cfg.num_candidates, 1)
        L = min(seq_len, gcfg.n_positions)
        params = module.init(key,
                             jnp.zeros((1, C, L), jnp.int32),
                             jnp.zeros((1, C, L), jnp.int32),
                             jnp.zeros((1, C), jnp.int32))
    return module, params


def main(argv=None) -> bool:
    enable_persistent_compilation_cache()
    cfg = parse_args(default_lr=4e-2, argv=argv)
    if cfg.multihost:
        # must precede every backend touch (jax.device_count below)
        mh.initialize_from_config(cfg)
    if cfg.do_test:
        # smoke shrink of the compression geometry (cv_train applies
        # the same pattern; reference cv_train.py:329-336)
        cfg = cfg.replace(num_rows=1, num_cols=1000, k=10, num_blocks=1)
    if mh.is_coordinator():
        print(cfg)
    timer = Timer()
    np.random.seed(cfg.seed)

    tokenizer = make_tokenizer(cfg.model_checkpoint,
                               fallback_vocab=500 if cfg.do_test else 5000)
    train_loader, val_loader = get_data_loaders(cfg, tokenizer)
    # each split pads to its own corpus max; position embeddings must
    # cover both (out-of-range ids would silently clamp, not raise)
    seq_len = max(train_loader.dataset.seq_len,
                  val_loader.dataset.seq_len)

    # --finetune redirects the model source to the finetuned artifact
    # (reference swaps model_checkpoint = finetune_path,
    # gpt2_train.py:270-272; it skips the swap under --test because its
    # finetune_path then names a full HF checkpoint — here a --test
    # smoke SAVES a loadable tiny artifact, so honor one when present)
    source = cfg.model_checkpoint
    if cfg.do_finetune and (
            not cfg.do_test
            or any(os.path.isfile(os.path.join(cfg.finetune_path, f))
                   for f in ("pytorch_model.bin", "pytorch_model.npz"))):
        source = cfg.finetune_path

    module, params = build_model_and_params(
        cfg, tokenizer, seq_len, source=source,
        require_load=(source == cfg.finetune_path and cfg.do_finetune))

    loss_train = make_compute_loss_train(module, cfg)
    loss_val = make_compute_loss_val(module)
    mesh = None
    if cfg.model_parallel > 1:
        # (clients, model) mesh: manual DP over clients, GSPMD tensor
        # parallelism over the model axis (parallel/tp.py); slice-major
        # clients layout auto-detected or emulated via --num_slices
        # (parallel/mesh.py), so TP activation collectives stay on ICI
        shards = max(len(jax.devices()) // cfg.model_parallel, 1)
        while cfg.num_workers % shards:
            shards -= 1
        mesh = make_multihost_client_mesh(
            model_parallel=cfg.model_parallel,
            devices=jax.devices()[:shards * cfg.model_parallel],
            num_slices=cfg.num_slices if cfg.num_slices > 1 else None)
        loss_train = tp_loss(loss_train, mesh)
        loss_val = tp_loss(loss_val, mesh)
        if mh.is_coordinator():
            print(f"tensor parallel: mesh {dict(mesh.shape)}")

    model = FedModel(None, loss_train, cfg, loss_val=loss_val,
                     params=params, mesh=mesh,
                     num_clients=train_loader.dataset.num_clients)
    opt = FedOptimizer(model)

    # round scheduler, attached BEFORE --resume so sched_* checkpoint
    # counters restore into this instance (wiring shared with
    # cv_train; uniform/no-deadline default is bit-identical)
    from commefficient_tpu.scheduler import attach_round_scheduler
    attach_round_scheduler(model, train_loader)

    # coordinator-broadcast control plane (ISSUE 12): the configured
    # plan transport rides on the scheduler above — wiring shared
    # with cv_train (parallel/plantransport.attach_config_transport)
    from commefficient_tpu.parallel.plantransport import (
        attach_config_transport,
    )
    attach_config_transport(model, train_loader, cfg)

    coord = mh.is_coordinator()
    if mh.is_multihost():
        # per-process batch feeding — or, on non-contiguous layouts,
        # the globalize() fallback (one shared implementation:
        # multihost.apply_feed_slices)
        mh.apply_feed_slices(model, train_loader, val_loader,
                             cfg.num_workers, val_loader.num_shards)

    spe = train_loader.steps_per_epoch
    if coord:
        print("Steps per epoch", spe)
    schedule = PiecewiseLinear([0, cfg.num_epochs * spe],
                               [cfg.lr_scale, 0.0])
    lr_scheduler = LambdaLR(opt, lr_lambda=schedule)

    # mid-run resume, symmetric with cv_train.main: newest rotated
    # checkpoint via the manifest, legacy fixed-name fallback,
    # fingerprint-validated (utils/checkpoint)
    ckpt_path = os.path.join(cfg.checkpoint_path, "gpt2")
    ckpt_fallbacks = []
    if cfg.resume:
        # corruption-tolerant resume (ISSUE 12 satellite, shared
        # contract with cv_train): checksum-verify the newest rotated
        # checkpoint and fall back to the previous rotation on a
        # corrupt/truncated file, journaling `checkpoint_fallback`
        # once the telemetry session exists
        from commefficient_tpu.utils.checkpoint import load_resilient
        loaded = load_resilient(
            ckpt_path,
            expect_fingerprint=model.checkpoint_fingerprint,
            on_fallback=lambda p, why: ckpt_fallbacks.append((p, why)))
        if loaded is not None:
            ck_file, ckpt = loaded
            lr_scheduler.load_state_dict(
                {"step_count": model.load_state(ckpt)})
            if coord:
                print(f"resumed from {ck_file} at round "
                      f"{int(ckpt.server.round_idx)}")
        if model.plan_transport is not None and cfg.journal_path:
            # deterministic restart: cross-check replayed rounds
            # against the pre-crash write-ahead plan stream
            model.load_plan_stream(cfg.journal_path)

    # only the coordinator creates a run dir (its artifacts are the
    # run's outputs; workers would just litter empty dirs)
    log_dir = make_logdir(cfg) if coord else ""
    # run journal + on-device metrics + throughput tracking (wiring
    # shared with the CV driver, owned by the telemetry package)
    from commefficient_tpu.telemetry import attach_run_telemetry
    tele = attach_run_telemetry(model, cfg, log_dir, coord,
                                driver="gpt2_train",
                                materialize=mh.gather_host)
    if tele is not None:
        for p, why in ckpt_fallbacks:
            tele.journal_event("checkpoint_fallback", path=p,
                               error=why[:200])
    if coord:
        print(f"Finished initializing in {timer():.2f} seconds")

    ok = False
    try:
        if cfg.do_finetune:
            test_gpt2(model, val_loader, timer=timer,
                      logger=TableLogger() if coord else NullLogger())
            ok = True
        else:
            from commefficient_tpu.telemetry import NumericTripError
            trips = 0
            while True:
                try:
                    ok = train_gpt2(model, opt, lr_scheduler,
                                    train_loader, cfg,
                                    logger=TableLogger() if coord
                                    else NullLogger(),
                                    timer=timer, log_dir=log_dir)
                    break
                except NumericTripError as trip:
                    # finite-frontier auto-rollback (ISSUE 16),
                    # shared contract with cv_train: walk back to
                    # the newest finite checkpoint, replay with
                    # screening forced on; bounded, then fail loud
                    trips += 1
                    if trips > cfg.max_numeric_rollbacks:
                        raise
                    sched_step = numeric_rollback(
                        model, ckpt_path, cfg, tele, trip)
                    if sched_step is None:
                        raise
                    lr_scheduler.load_state_dict(
                        {"step_count": sched_step})
            save_checkpoint(os.path.join(log_dir, "gpt2"), model.server,
                            scheduler_step=lr_scheduler.step_count)
            if cfg.do_checkpoint:
                # stamped + manifest (what --resume prefers) AND the
                # fixed-name artifact, in one collective gather
                model.drain_persistence()
                save_final(ckpt_path, model.server, model.clients,
                           keep_last=cfg.keep_checkpoints,
                           max_age_hours=cfg.ckpt_max_age_hours,
                           scheduler_step=lr_scheduler.step_count,
                           accountant=model.accountant,
                           prev_change_words=model._prev_change_words,
                           fingerprint=model.checkpoint_fingerprint,
                           throughput=model.throughput.state_dict(),
                           scheduler=model.scheduler_state(),
                           sampler=model.sampler_state(),
                           async_admit=model.async_admit_state(),
                           client_rows=model.client_rows_payload())
            # HF-style final artifact: tokenizer + config + weights
            # (reference gpt2_train.py:275-283, fed_aggregator.py:208-211)
            if coord:
                save_pretrained(log_dir, model.state_dict(), module.cfg,
                                tokenizer)
            # the final eval legitimately first-compiles after the
            # train loop's steady state — not a retrace warning
            with (tele.expect_compiles("final eval") if tele is not None
                  else contextlib.nullcontext()):
                test_gpt2(model, val_loader, timer=timer,
                          logger=TableLogger() if coord
                          else NullLogger())
        model.finalize()
    finally:
        # close even when training raises (fault drill, NaN abort):
        # the global compile listener and any live profiler capture
        # must not leak into the next in-process run. The persistence
        # writer drains FIRST (--pipeline): a queued span checkpoint
        # flushes at a crash exactly like at a clean shutdown.
        try:
            model.close_persistence()
        finally:
            if tele is not None:
                tele.close(ok=bool(ok))
    return ok


def cli() -> None:
    """Console entry point (`gpt2-train`, pyproject.toml)."""
    raise SystemExit(0 if main() else 1)


if __name__ == "__main__":
    cli()

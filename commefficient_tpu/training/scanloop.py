"""Shared staging loop for --scan_rounds: collect rounds into spans,
run each span as ONE scanned device program (FedModel.run_rounds), and
emit per-round metrics.

Both drivers run the same mechanics (span_cap derivation, host-side
[N, W, B, ...] staging, the np.stack flush, the partial tail span) and
previously each carried its own copy; only what they DO with a round's
metric rows differs, so that part is the `emit` callback.
"""
from __future__ import annotations

from typing import Callable, Iterable, Optional, Sequence, Tuple

import numpy as np


def run_scanned_rounds(model, stream: Iterable[Tuple],
                       span_cap: int,
                       emit: Callable[..., bool],
                       on_comm: Optional[Callable[[np.ndarray, np.ndarray],
                                                  None]] = None,
                       on_flush: Optional[Callable[[int], None]] = None
                       ) -> bool:
    """Drive scanned spans over `stream`, which yields
    (tag, client_ids, data_tuple, mask, lr) per round — the caller owns
    round-budget/epoch-boundary logic by just ending the stream.

    Per flushed span: on_flush(n_rounds) once as soon as the span's
    device program has returned (per-round wall-time attribution — a
    scanned span has no per-round boundaries, so callers amortize),
    then on_comm(download, upload) once (host accounting totals), then
    emit(tag, *per_round_metric_rows) once per round IN ORDER. emit
    returning False aborts immediately (the remaining rounds of the
    span are neither emitted nor logged — matching the unscanned loop,
    which stops at the first bad round).

    Returns True if every emit succeeded, False on abort.
    """
    ids, datas, masks, lrs, tags = [], [], [], [], []

    def flush() -> bool:
        out = model.run_rounds(
            np.stack(ids),
            tuple(np.stack([dd[i] for dd in datas])
                  for i in range(len(datas[0]))),
            np.stack(masks), np.asarray(lrs))
        *metric_rows, down, up = out
        if on_flush is not None:
            on_flush(len(ids))
        if on_comm is not None:
            on_comm(down, up)
        for n in range(len(ids)):
            if not emit(tags[n], *[m[n] for m in metric_rows]):
                return False
        return True

    for tag, client_ids, data, mask, lr in stream:
        ids.append(client_ids)
        datas.append(data)
        masks.append(mask)
        lrs.append(lr)
        tags.append(tag)
        if len(ids) == span_cap:
            if not flush():
                return False
            ids, datas, masks, lrs, tags = [], [], [], [], []
    if ids:
        return flush()
    return True

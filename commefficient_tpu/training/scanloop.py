"""Shared staging loop for --scan_rounds: collect rounds into spans,
run each span as ONE scanned device program (FedModel.run_rounds), and
emit per-round metrics.

Both drivers run the same mechanics (span_cap derivation, host-side
[N, W, B, ...] staging, the np.stack flush, the partial tail span) and
previously each carried its own copy; only what they DO with a round's
metric rows differs, so that part is the `emit` callback.
"""
from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterable, Optional, Sequence, Tuple

import numpy as np


def run_scanned_rounds(model, stream: Iterable[Tuple],
                       span_cap: int,
                       emit: Callable[..., bool],
                       on_comm: Optional[Callable[[np.ndarray, np.ndarray],
                                                  None]] = None,
                       on_flush: Optional[Callable[[int], None]] = None,
                       checkpoint: Optional[Callable[[], None]] = None,
                       guard: Optional[Callable] = None
                       ) -> bool:
    """Drive scanned spans over `stream`, which yields
    (tag, client_ids, data_tuple, mask, lr) per round — the caller owns
    round-budget/epoch-boundary logic by just ending the stream.

    Per flushed span: on_flush(n_rounds) once as soon as the span's
    device program has returned (per-round wall-time attribution — a
    scanned span has no per-round boundaries, so callers amortize),
    then on_comm(download, upload) once (host accounting totals), then
    checkpoint() once, then emit(tag, *per_round_metric_rows) once per
    round IN ORDER. emit returning False aborts immediately (the
    remaining rounds of the span are neither emitted nor logged —
    matching the unscanned loop, which stops at the first bad round).

    `checkpoint` is the mid-span-preemption survival hook: a span is
    the atomic commit unit of scanned training (a preemption while a
    span's device program is in flight loses everything since the last
    span boundary — FedModel.run_rounds, FaultSchedule.crash_in_span),
    so checkpointing at every boundary — AFTER the span's state and
    accounting have committed, BEFORE emits that might abort — bounds
    the loss of a kill at any instant to one span. Callers pass a
    closure over utils/checkpoint.save_rotating; tests prove resume
    from the hook's checkpoint is bit-exact to the uninterrupted run.

    `guard` is the --debug_transfer_guard hook: a context-manager
    factory (analysis/runtime.forbid_transfers) armed around every
    span's dispatch EXCEPT the model's first — the first span compiles
    its scanned program, everything after is the steady state whose
    zero-implicit-transfer contract the guard enforces at runtime.
    The span index lives ON THE MODEL (`_spans_dispatched`), because
    the drivers call run_scanned_rounds once per epoch: a local
    counter would re-exempt (and re-profile) each epoch's first span,
    which is long past compilation.

    A model with an attached telemetry.TelemetrySession additionally
    gets jax.profiler capture of --profile_spans span indices (global
    across the run, same model-held counter): the session's
    span_profile_begin/end bracket each flush, so the trace covers
    exactly the requested spans' real device work.

    Returns True if every emit succeeded, False on abort.
    """
    ids, datas, masks, lrs, tags = [], [], [], [], []

    def flush() -> bool:
        span_idx = getattr(model, "_spans_dispatched", 0)
        tele = getattr(model, "telemetry", None)
        if tele is not None:
            tele.span_profile_begin(span_idx)
        ctx = (guard() if guard is not None and span_idx > 0
               else contextlib.nullcontext())
        with ctx:
            out = model.run_rounds(
                np.stack(ids),
                tuple(np.stack([dd[i] for dd in datas])
                      for i in range(len(datas[0]))),
                np.stack(masks), np.asarray(lrs))
        if tele is not None:
            tele.span_profile_end(span_idx)
        model._spans_dispatched = span_idx + 1
        *metric_rows, down, up = out
        if on_flush is not None:
            on_flush(len(ids))
        if on_comm is not None:
            on_comm(down, up)
        if checkpoint is not None:
            checkpoint()
        for n in range(len(ids)):
            if not emit(tags[n], *[m[n] for m in metric_rows]):
                return False
        return True

    for tag, client_ids, data, mask, lr in stream:
        ids.append(client_ids)
        datas.append(data)
        masks.append(mask)
        lrs.append(lr)
        tags.append(tag)
        if len(ids) == span_cap:
            if not flush():
                return False
            ids, datas, masks, lrs, tags = [], [], [], [], []
    if ids:
        return flush()
    return True


def make_span_checkpoint(prefix: str, model, cfg, lr_scheduler):
    """Build the drivers' shared `checkpoint` hook for
    run_scanned_rounds: a rotated save (utils/checkpoint.save_rotating)
    at every cfg.ckpt_every_spans-th span boundary. Returns None when
    span-boundary saving is off — checkpointing disabled entirely
    (checkpoint_every=0) or cadence 0 (epoch-cadence saves only).

    Each save is a full server+client state gather plus a disk write,
    which is why the cadence is a knob: 1 (the default) bounds a
    mid-span preemption's loss to one span, larger values trade
    recovery granularity for save rate on big models."""
    if not (cfg.checkpoint_every and cfg.ckpt_every_spans):
        return None
    from commefficient_tpu.parallel import multihost as mh
    from commefficient_tpu.utils.checkpoint import save_rotating

    spans_done = [0]

    def span_checkpoint():
        spans_done[0] += 1
        if spans_done[0] % cfg.ckpt_every_spans:
            return
        t0 = time.monotonic()
        path = save_rotating(
            prefix, model.server, model.clients,
            keep_last=cfg.keep_checkpoints,
            max_age_hours=cfg.ckpt_max_age_hours,
            scheduler_step=lr_scheduler.step_count,
            accountant=model.accountant,
            prev_change_words=model._prev_change_words,
            fingerprint=model.checkpoint_fingerprint,
            throughput=model.throughput.state_dict(),
            scheduler=model.scheduler_state(),
            sampler=model.sampler_state(),
            client_rows=model.client_rows_payload())
        tele = getattr(model, "telemetry", None)
        if tele is not None:
            # the save is a full state gather + disk write — exactly
            # the wall-clock span the journal exists to attribute
            tele.journal_event("checkpoint", path=path,
                               seconds=round(time.monotonic() - t0, 3),
                               span_boundary=True)
        if mh.is_coordinator():
            print(f"checkpointed to {path}")

    return span_checkpoint

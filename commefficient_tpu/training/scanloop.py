"""Shared staging loop for --scan_rounds: collect rounds into spans,
run each span as ONE scanned device program (FedModel.run_rounds), and
emit per-round metrics.

Both drivers run the same mechanics (span_cap derivation, host-side
[N, W, B, ...] staging, the np.stack flush, the partial tail span) and
previously each carried its own copy; only what they DO with a round's
metric rows differs, so that part is the `emit` callback.

Pipelined mode (ISSUE 10, Config.pipeline / `pipeline=True` here)
double-buffers the dispatch: a span is DISPATCHED as soon as it is
staged (FedModel.dispatch_rounds — asynchronous, the device starts as
soon as its predecessor finishes) and COLLECTED one flush later
(FedModel.collect_rounds — accounting, journal, checkpoint, emits),
so span t+1's host staging (sampler draws, batch fetch/transform,
np.stack, fault operands, explicit placement) and span t-1's
persistence overlap span t's device execution. The synchronous path
(`pipeline=False`, the default) is the identical code running the two
halves back-to-back — bit-identical to the pre-feature loop.
"""
from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterable, Optional, Sequence, Tuple

import numpy as np


def run_scanned_rounds(model, stream: Iterable[Tuple],
                       span_cap,
                       emit: Callable[..., bool],
                       on_comm: Optional[Callable[[np.ndarray, np.ndarray],
                                                  None]] = None,
                       on_flush: Optional[Callable[[int], None]] = None,
                       checkpoint: Optional[Callable[[], None]] = None,
                       guard: Optional[Callable] = None,
                       pipeline: bool = False
                       ) -> bool:
    """Drive scanned spans over `stream`, which yields
    (tag, client_ids, data_tuple, mask, lr) per round — the caller owns
    round-budget/epoch-boundary logic by just ending the stream.

    `span_cap` is either a static int (the pre-ISSUE-20 contract) or
    an adaptive provider exposing `span_cap(default) -> int` and
    `tail_cap(leftover) -> int` (the model's ControllerBank when a
    span-cadence controller is attached). Adaptive mode latches the
    provider's live pick at each span's START — a mid-span adjustment
    (a collect-time cadence feed, a replayed plan's install) can only
    ever resize the NEXT span, so a flush never stages an off-palette
    (untraced) shape — and decomposes the stream tail greedily over
    `tail_cap`, largest already-traced length first, down to the
    guaranteed 1-span (Config.validate requires 1 in the palette).

    Per flushed span: on_flush(n_rounds) once as soon as the span's
    device program has returned (per-round wall-time attribution — a
    scanned span has no per-round boundaries, so callers amortize),
    then on_comm(download, upload) once (host accounting totals), then
    checkpoint() once, then emit(tag, *per_round_metric_rows) once per
    round IN ORDER. emit returning False aborts immediately (the
    remaining rounds of the span are neither emitted nor logged —
    matching the unscanned loop, which stops at the first bad round).

    `checkpoint` is the mid-span-preemption survival hook: a span is
    the atomic commit unit of scanned training (a preemption while a
    span's device program is in flight loses everything since the last
    span boundary — FedModel.run_rounds, FaultSchedule.crash_in_span),
    so checkpointing at every boundary — AFTER the span's state and
    accounting have committed, BEFORE emits that might abort — bounds
    the loss of a kill at any instant to one span. Callers pass a
    closure over utils/checkpoint.save_rotating; tests prove resume
    from the hook's checkpoint is bit-exact to the uninterrupted run.

    `guard` is the --debug_transfer_guard hook: a context-manager
    factory (analysis/runtime.forbid_transfers) armed around every
    span's dispatch EXCEPT the model's first — the first span compiles
    its scanned program, everything after is the steady state whose
    zero-implicit-transfer contract the guard enforces at runtime.
    The span index lives ON THE MODEL (`_spans_dispatched`), because
    the drivers call run_scanned_rounds once per epoch: a local
    counter would re-exempt (and re-profile) each epoch's first span,
    which is long past compilation.

    A model with an attached telemetry.TelemetrySession additionally
    gets jax.profiler capture of --profile_spans span indices (global
    across the run, same model-held counter): the session's
    span_profile_begin/end bracket each flush, so the trace covers
    exactly the requested spans' real device work.

    `pipeline=True` (Config.pipeline) defers each span's commit —
    on_flush/on_comm/checkpoint/emits — to the NEXT flush, after the
    following span has already been dispatched (module docstring).
    Three contracts shift, all bounded by one span: a NaN/emit abort
    lands one span later (the next span's state has already committed
    by then, exactly like the sync path's abort-after-commit
    semantics); an injected crash while two spans are in flight loses
    both back to the last *persisted* boundary (a real preemption
    does too); and the checkpoint hook receives the SNAPSHOT captured
    at the span's own boundary — state references plus the sampler/
    scheduler/admit-buffer cursors as of that span's draws — via the
    hook's `snapshot` kwarg (make_span_checkpoint provides the
    `.snapshot` factory; hooks without one are called with no
    arguments and read live state, which in pipelined mode is one
    span ahead — use make_span_checkpoint). A prefetch lost to a
    crash (span t+1's draws when the run dies collecting span t)
    replays from the checkpointed sampler cursor: the snapshot was
    taken BEFORE the prefetch advanced it.

    Returns True if every emit succeeded, False on abort.
    """
    ids, datas, masks, lrs, tags = [], [], [], [], []
    snapshot_fn = getattr(checkpoint, "snapshot", None)
    # pipelined double buffer: the one dispatched-but-uncollected span
    pending = []  # [(handle, tags, span_idx, snapshot)]

    def commit(out, span_tags, snap) -> bool:
        """The span's host-side commit: wall-time/comm callbacks,
        the boundary checkpoint, then the per-round emits."""
        *metric_rows, down, up = out
        if on_flush is not None:
            on_flush(len(span_tags))
        if on_comm is not None:
            on_comm(down, up)
        if checkpoint is not None:
            if snap is not None:
                checkpoint(snapshot=snap)
            else:
                checkpoint()
        for n in range(len(span_tags)):
            if not emit(span_tags[n], *[m[n] for m in metric_rows]):
                return False
        return True

    def collect_pending() -> bool:
        handle, span_tags, span_idx, snap = pending.pop()
        tele = getattr(model, "telemetry", None)
        out = model.collect_rounds(handle)
        if tele is not None:
            tele.span_profile_end(span_idx)
        return commit(out, span_tags, snap)

    def drain_pending_on_abort() -> None:
        """An emit abort surfaces one span late in pipelined mode, with
        the NEXT span already dispatched (its state assigned to the
        model). Collect that span's accounting/telemetry — and feed
        on_flush/on_comm — so the model's accountant, change-bitset lag
        and byte totals stay consistent with its (already advanced)
        weights for the drivers' post-abort saves; skip its emits (the
        run is aborting) and its boundary checkpoint (a NaN abort must
        not poison --resume with a post-abort state)."""
        if not pending:
            return
        handle, span_tags, span_idx, _ = pending.pop()
        tele = getattr(model, "telemetry", None)
        out = model.collect_rounds(handle)
        if tele is not None:
            tele.span_profile_end(span_idx)
        *_, down, up = out
        if on_flush is not None:
            on_flush(len(span_tags))
        if on_comm is not None:
            on_comm(down, up)

    def flush() -> bool:
        span_idx = getattr(model, "_spans_dispatched", 0)
        tele = getattr(model, "telemetry", None)
        if tele is not None:
            tele.span_profile_begin(span_idx)
        ctx = (guard() if guard is not None and span_idx > 0
               else contextlib.nullcontext())
        args = (np.stack(ids),
                tuple(np.stack([dd[i] for dd in datas])
                      for i in range(len(datas[0]))),
                np.stack(masks), np.asarray(lrs))
        if not pipeline:
            with ctx:
                out = model.run_rounds(*args)
            if tele is not None:
                tele.span_profile_end(span_idx)
            model._spans_dispatched = span_idx + 1
            return commit(out, list(tags), None)
        # pipelined: an injected crash boundary in the PENDING span
        # must surface before more work dispatches (the sync path
        # raised inside its own flush) — collect it first, which
        # raises InjectedFault at the same round boundary
        if pending and pending[0][0].crash_at is not None:
            collect_pending()
        with ctx:
            handle = model.dispatch_rounds(*args)
        model._spans_dispatched = span_idx + 1
        # the span's boundary snapshot: state refs (the span program's
        # result futures, just assigned) + the persistent-stream
        # cursors as of THIS span's draws — captured before the next
        # span's pulls advance them
        snap = snapshot_fn() if snapshot_fn is not None else None
        prev_ok = True
        if pending:
            prev_ok = collect_pending()
        if snap is not None:
            # the throughput tracker commits at COLLECT time, so it is
            # captured AFTER the previous span's collect: exactly the
            # state the NEXT span's selection draws will observe. A
            # resume from this boundary re-draws that span against the
            # identical tracker — saving the live (one-span-richer)
            # state at save time instead would silently diverge a
            # throughput-sampled resumed stream.
            snap["throughput"] = model.throughput.state_dict()
        pending.append((handle, list(tags), span_idx, snap))
        return prev_ok

    adaptive = hasattr(span_cap, "span_cap")
    cap = None
    for tag, client_ids, data, mask, lr in stream:
        if cap is None:
            cap = (int(span_cap.span_cap(1)) if adaptive
                   else int(span_cap))
        ids.append(client_ids)
        datas.append(data)
        masks.append(mask)
        lrs.append(lr)
        tags.append(tag)
        if len(ids) == cap:
            if not flush():
                drain_pending_on_abort()
                return False
            ids, datas, masks, lrs, tags = [], [], [], [], []
            cap = None
    while ids:
        # stream tail: static mode flushes the leftover as one span
        # (its own traced shape, as before); adaptive mode decomposes
        # it over already-traced palette lengths
        take = (max(1, min(int(span_cap.tail_cap(len(ids))),
                           len(ids)))
                if adaptive else len(ids))
        rest = None
        if take < len(ids):
            rest = (ids[take:], datas[take:], masks[take:],
                    lrs[take:], tags[take:])
            ids, datas, masks, lrs, tags = (
                ids[:take], datas[:take], masks[:take], lrs[:take],
                tags[:take])
        if not flush():
            drain_pending_on_abort()
            return False
        if rest is None:
            break
        ids, datas, masks, lrs, tags = rest
    if pending:
        return collect_pending()
    return True


def numeric_rollback(model, prefix: str, cfg, tele, trip):
    """Finite-frontier auto-rollback (ISSUE 16), shared by both
    drivers: after telemetry raises NumericTripError (a watched
    update/error-l2 went non-finite; the `numeric_trip` journal
    record is already durable), walk the checkpoint rotation back to
    the newest entry whose manifest records FINITE state
    (utils/checkpoint.load_resilient require_finite), restore it, and
    force update screening on for the next cfg.rollback_screen_rounds
    rounds so the replayed window admits out whatever poisoned the
    frontier. The caller re-enters its training loop; the restored
    round counter + sampler/scheduler cursors make the resumed stream
    bit-exact from the rolled-back boundary.

    Returns the restored scheduler step, or None when no finite
    checkpoint exists — the caller re-raises the trip (fail loud
    rather than train forward from a poisoned frontier)."""
    from commefficient_tpu.parallel import multihost as mh
    from commefficient_tpu.utils.checkpoint import load_resilient

    model.drain_persistence()
    if tele is not None:
        # drop the one-round-lag metric buffer: it likely carries the
        # same non-finite row and would re-trip against the rollback
        # budget the moment training resumes
        tele.discard_pending()
    fallbacks = []
    loaded = load_resilient(
        prefix, expect_fingerprint=model.checkpoint_fingerprint,
        on_fallback=lambda p, why: fallbacks.append((p, why)),
        require_finite=True)
    if tele is not None:
        for p, why in fallbacks:
            tele.journal_event("checkpoint_fallback", path=p,
                               error=why[:200])
    if loaded is None:
        return None
    path, ckpt = loaded
    sched_step = model.load_state(ckpt)
    # AFTER load_state: the forced-screen window counts from the
    # restored round counter, covering exactly the replayed rounds
    model.force_screen_rounds(cfg.rollback_screen_rounds)
    if mh.is_coordinator():
        print(f"numeric trip at round {trip.round_idx} "
              f"({', '.join(trip.metrics) or 'telemetry'}): rolled "
              f"back to {path} (round {int(ckpt.server.round_idx)}); "
              f"update screening forced for "
              f"{cfg.rollback_screen_rounds} rounds")
    return sched_step


def make_span_checkpoint(prefix: str, model, cfg, lr_scheduler):
    """Build the drivers' shared `checkpoint` hook for
    run_scanned_rounds: a rotated save (utils/checkpoint.save_rotating)
    at every cfg.ckpt_every_spans-th span boundary. Returns None when
    span-boundary saving is off — checkpointing disabled entirely
    (checkpoint_every=0) or cadence 0 (epoch-cadence saves only).

    Each save is a full server+client state gather plus a disk write,
    which is why the cadence is a knob: 1 (the default) bounds a
    mid-span preemption's loss to one span, larger values trade
    recovery granularity for save rate on big models.

    The hook carries a `.snapshot` attribute — the pipelined staging
    loop calls it at each span's own boundary (right after dispatch,
    before the next span's draws) and hands the result back through
    the hook's `snapshot` kwarg, so a one-span-late save persists the
    RIGHT span: its state references and the sampler/scheduler/
    admit-buffer cursors as of its draws, not the live (one-span-
    ahead) ones. Under Config.pipeline the serialization itself rides
    the model's AsyncCheckpointWriter — the gather happens here, the
    np.savez/fsync/rename on the writer thread."""
    if not (cfg.checkpoint_every and cfg.ckpt_every_spans):
        return None
    from commefficient_tpu.parallel import multihost as mh
    from commefficient_tpu.telemetry.trace import TRACE
    from commefficient_tpu.utils.checkpoint import save_rotating

    spans_done = [0]

    def take_snapshot() -> dict:
        # captured at the span's own boundary (pipelined: right after
        # its dispatch, before the next span's draws). Deliberately
        # NOT here: _prev_change_words, the accountant, and the
        # throughput tracker — those commit at COLLECT time in span
        # order, so the live read at save time is the span-consistent
        # one on both paths.
        snap = {
            "server": model.server,
            "clients": model.clients,
            "scheduler_step": lr_scheduler.step_count,
            "sampler": model.sampler_state(),
            "scheduler": model.scheduler_state(),
            "async_admit": model.async_admit_state(),
        }
        store = getattr(model, "state_store", None)
        if store is not None:
            # tiered client state (ISSUE 11): the LRU/touched
            # bookkeeping advances with the NEXT span's staging, so a
            # one-span-late save needs the boundary-time copy — cheap
            # host arrays; the O(working set) device gather still
            # happens at save time, against the snapshot's block
            snap["tier"] = store.snapshot_tier()
        return snap

    def span_checkpoint(snapshot=None):
        spans_done[0] += 1
        if spans_done[0] % cfg.ckpt_every_spans:
            return
        if snapshot is None:
            snapshot = take_snapshot()
        bank = getattr(model, "control_bank", None)
        if bank is not None:
            commit_keys = bank.commit_state_dict()
            if commit_keys:
                # commit-time controller state (the staleness ring)
                # advances at COLLECT time in span order — by save
                # time this span HAS collected, so the live read is
                # the span-consistent one; the dispatch-time snapshot
                # predates the previous span's collect under
                # --pipeline (same discipline as the accountant and
                # _prev_change_words above)
                snapshot["scheduler"] = {**snapshot["scheduler"],
                                         **commit_keys}
        t0 = time.monotonic()
        # graftscope (ISSUE 13): the boundary save as a `checkpoint`
        # stage span (gather + serialize, or gather + enqueue under
        # the async writer — whose own qwait/write spans inherit this
        # span's round tag through the submit path)
        with TRACE.span("checkpoint",
                        round=int(getattr(model, "_rounds_done", 0))):
            path = save_rotating(
                prefix, snapshot["server"], snapshot["clients"],
                keep_last=cfg.keep_checkpoints,
                max_age_hours=cfg.ckpt_max_age_hours,
                scheduler_step=snapshot["scheduler_step"],
                accountant=model.accountant,
                prev_change_words=model._prev_change_words,
                fingerprint=model.checkpoint_fingerprint,
                # pipelined snapshots carry the tracker state the
                # next span's draws observed (captured post-collect
                # in the staging loop); the sync path reads live —
                # same value there, since nothing collected in
                # between
                throughput=(snapshot["throughput"]
                            if "throughput" in snapshot
                            else model.throughput.state_dict()),
                scheduler=snapshot["scheduler"],
                sampler=snapshot["sampler"],
                async_admit=snapshot["async_admit"],
                client_rows=model.client_rows_payload(
                    clients=snapshot["clients"],
                    tier=snapshot.get("tier")),
                writer=model.ckpt_writer)
        tele = getattr(model, "telemetry", None)
        if tele is not None:
            # the save is a full state gather + disk write — exactly
            # the wall-clock span the journal exists to attribute
            # (under the async writer, `seconds` covers the gather
            # and queueing; the write itself is off-path by design)
            tele.journal_event("checkpoint", path=path,
                               seconds=round(time.monotonic() - t0, 3),
                               span_boundary=True)
        if mh.is_coordinator():
            print(f"checkpointed to {path}")

    span_checkpoint.snapshot = take_snapshot
    return span_checkpoint

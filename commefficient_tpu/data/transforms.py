"""Batched numpy augmentation pipelines, NHWC.

Capability parity with the reference's per-dataset torchvision
pipelines (reference: CommEfficient/data_utils/transforms.py:17-75),
re-designed for TPU input pipelines: transforms are *vectorized over
the whole batch* on the host (a single fancy-index gather per batch
instead of Python-per-image PIL work), emitting float32 NHWC arrays
ready for device transfer. Normalization constants match the
reference exactly.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2471, 0.2435, 0.2616], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4867, 0.4408], np.float32)
CIFAR100_STD = np.array([0.2675, 0.2565, 0.2761], np.float32)
FEMNIST_MEAN = np.array([0.9637], np.float32)
FEMNIST_STD = np.array([0.1597], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _to_float(images: np.ndarray) -> np.ndarray:
    if images.dtype == np.uint8:
        return images.astype(np.float32) / 255.0
    return images.astype(np.float32)


def normalize(images: np.ndarray, mean: np.ndarray,
              std: np.ndarray) -> np.ndarray:
    return (_to_float(images) - mean) / std


def random_crop_reflect(images: np.ndarray, pad: int,
                        rng: np.random.RandomState) -> np.ndarray:
    """Batched RandomCrop(size, padding=pad, reflect)."""
    n, h, w, _ = images.shape
    padded = np.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")
    ys = rng.randint(0, 2 * pad + 1, size=n)
    xs = rng.randint(0, 2 * pad + 1, size=n)
    # vectorized window gather
    yy = ys[:, None] + np.arange(h)[None, :]
    out = padded[np.arange(n)[:, None], yy][:, :, :]
    xx = xs[:, None] + np.arange(w)[None, :]
    out = out[np.arange(n)[:, None, None],
              np.arange(h)[None, :, None], xx[:, None, :]]
    return out


def random_hflip(images: np.ndarray,
                 rng: np.random.RandomState) -> np.ndarray:
    flip = rng.rand(images.shape[0]) < 0.5
    out = images.copy()
    out[flip] = out[flip, :, ::-1]
    return out


def _make_cifar_transforms(mean, std, seed=0):
    rng = np.random.RandomState(seed)

    def train(images, labels):
        x = random_crop_reflect(images, 4, rng)
        x = random_hflip(x, rng)
        return normalize(x, mean, std), labels.astype(np.int32)

    def test(images, labels):
        return normalize(images, mean, std), labels.astype(np.int32)

    return train, test


def cifar10_transforms(seed=0):
    return _make_cifar_transforms(CIFAR10_MEAN, CIFAR10_STD, seed)


def cifar100_transforms(seed=0):
    return _make_cifar_transforms(CIFAR100_MEAN, CIFAR100_STD, seed)


def femnist_transforms(seed=0):
    """Crop-jitter + small rotation on 28x28x1 digits (reference
    transforms.py:47-54; the rotation/rescale distortions are
    approximated by shift + nearest-neighbor scale jitter — same
    augmentation intent without a per-image interpolation kernel)."""
    rng = np.random.RandomState(seed)

    def train(images, labels):
        x = _to_float(images)
        # constant-pad with white (fill=1.0) then random 28x28 crop
        n, h, w, c = x.shape
        pad = 2
        xp = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    constant_values=1.0)
        ys = rng.randint(0, 2 * pad + 1, size=n)
        xs = rng.randint(0, 2 * pad + 1, size=n)
        yy = ys[:, None] + np.arange(h)[None, :]
        out = xp[np.arange(n)[:, None], yy]
        xx = xs[:, None] + np.arange(w)[None, :]
        out = out[np.arange(n)[:, None, None],
                  np.arange(h)[None, :, None], xx[:, None, :]]
        return normalize(out, FEMNIST_MEAN, FEMNIST_STD), labels.astype(np.int32)

    def test(images, labels):
        return normalize(images, FEMNIST_MEAN, FEMNIST_STD), labels.astype(np.int32)

    return train, test


def imagenet_transforms(seed=0, size=224):
    """Random crop+flip / center crop at eval (reference
    transforms.py:66-75). Assumes pre-resized source images."""
    rng = np.random.RandomState(seed)

    def train(images, labels):
        x = random_hflip(images, rng)
        return normalize(x, IMAGENET_MEAN, IMAGENET_STD), labels.astype(np.int32)

    def test(images, labels):
        return normalize(images, IMAGENET_MEAN, IMAGENET_STD), labels.astype(np.int32)

    return train, test


TRANSFORMS = {
    "CIFAR10": cifar10_transforms,
    "CIFAR100": cifar100_transforms,
    "EMNIST": femnist_transforms,
    "ImageNet": imagenet_transforms,
}

"""Client sampling: which clients participate each round, with which
data — emitting *static-shape* padded batches.

Capability parity with the reference's FedSampler (reference:
CommEfficient/data_utils/fed_sampler.py:19-68): per epoch, permute data
within each client, then repeatedly draw `num_workers` non-exhausted
clients without replacement and take up to `local_batch_size` examples
from each (the whole remaining client dataset when -1).

TPU-first difference: the reference yields ragged index lists (variable
`actual_batch_sizes`, fed_sampler.py:55-62) and lets torch build
variable-size batches; XLA needs one compiled program, so every round
here is [num_workers, B] indices + an f32 validity mask, B fixed for
the whole run (SURVEY.md §7.3 hard part #2). Rounds with fewer than
num_workers non-exhausted clients end the epoch — the reference
*dispatches* such batches and then skips them in the driver
(cv_train.py:205-219), which is equivalent up to RNG state.
"""
from __future__ import annotations

from typing import Iterator, NamedTuple, Optional

import numpy as np


class RoundIndices(NamedTuple):
    client_ids: np.ndarray   # [num_workers] int32
    idx_within: np.ndarray   # [num_workers, B] int32 local indices
    mask: np.ndarray         # [num_workers, B] f32 validity


class FedSampler:
    def __init__(self, data_per_client: np.ndarray, num_workers: int,
                 local_batch_size: int, seed: int = 0,
                 shuffle_clients: bool = True,
                 max_local_batch: int = -1, scheduler=None):
        """max_local_batch caps the static batch dim B when
        local_batch_size == -1 (whole-client batches): a client with
        more data than the cap stays non-exhausted and participates in
        consecutive rounds on successive chunks. Bounds the
        [num_workers, B, ...] staging arrays that are otherwise sized
        by max(data_per_client) — the ImageNet-scale memory hazard.

        scheduler: optional RoundScheduler (commefficient_tpu/
        scheduler; also settable post-construction — drivers attach
        via scheduler.attach_round_scheduler). When set, participant
        selection is delegated to its policy; the UNIFORM default
        makes the byte-identical `rng.choice` call this class made
        before the scheduler existed, so the drawn stream — and
        everything downstream — is bit-unchanged. A policy may select
        FEWER than num_workers clients (over-provisioning targets);
        the surplus slots are padded with distinct UNCHOSEN client
        ids carrying all-zero masks — the scheduler marks them dead
        (survivor 0) so the jitted round leaves their state rows
        bit-untouched and accounting charges them nothing. Pad ids
        must be distinct from the chosen ids: the round engine's
        scatter-back writes every slot's row, and a duplicate
        alive/dead id pair would race the alive client's update."""
        self.data_per_client = np.asarray(data_per_client)
        self.num_clients = len(self.data_per_client)
        self.num_workers = num_workers
        self.local_batch_size = local_batch_size
        self.max_local_batch = max_local_batch
        self.rng = np.random.RandomState(seed)
        self.shuffle_clients = shuffle_clients
        self.scheduler = scheduler
        if num_workers > self.num_clients:
            raise ValueError(
                f"num_workers={num_workers} > num_clients={self.num_clients}")

    def _cap(self, take: np.ndarray | int):
        """Applies ONLY to whole-client (-1) batches, per the flag's
        documented contract; explicit local_batch_size is untouched."""
        if self.local_batch_size == -1 and self.max_local_batch > 0:
            return np.minimum(take, self.max_local_batch)
        return take

    @property
    def round_batch_size(self) -> int:
        """Static per-client batch dimension B."""
        if self.local_batch_size == -1:
            return int(self._cap(int(self.data_per_client.max())))
        return self.local_batch_size

    def steps_per_epoch(self) -> int:
        """(reference utils.py:315-321; capped whole-client batches
        count each client once per chunk)"""
        if self.local_batch_size == -1:
            if self.max_local_batch > 0:
                participations = int(np.ceil(
                    self.data_per_client / self.max_local_batch).sum())
                return max(participations // self.num_workers, 1)
            return int(self.num_clients // self.num_workers)
        total = int(self.data_per_client.sum())
        return int(np.ceil(total / (self.local_batch_size * self.num_workers)))

    def epoch(self) -> Iterator[RoundIndices]:
        B = self.round_batch_size
        dpc = self.data_per_client
        # per-client permutation of local indices
        perms = [self.rng.permutation(n) for n in dpc]
        cursor = np.zeros(self.num_clients, dtype=int)

        while True:
            alive = np.where(cursor < dpc)[0]
            if len(alive) < self.num_workers:
                return
            if self.scheduler is not None:
                # policy selection (possibly < num_workers under an
                # over-provisioning target); the uniform default makes
                # the identical rng.choice call the branch below does
                chosen = np.asarray(self.scheduler.select(
                    alive, self.num_workers, self.rng))
            else:
                chosen = self.rng.choice(alive, self.num_workers,
                                         replace=False)
            if len(chosen) < self.num_workers:
                # idle-slot padding: distinct ids NOT chosen this
                # round (num_clients >= num_workers guarantees
                # enough), zero-mask rows, cursor untouched — the
                # scheduler's plan marks them survivor-0
                pad = np.setdiff1d(np.arange(self.num_clients),
                                   chosen)[:self.num_workers
                                           - len(chosen)]
                slot_ids = np.concatenate([chosen, pad])
            else:
                slot_ids = chosen
            idx = np.zeros((self.num_workers, B), np.int32)
            mask = np.zeros((self.num_workers, B), np.float32)
            for w, cid in enumerate(chosen):
                remaining = dpc[cid] - cursor[cid]
                take = remaining if self.local_batch_size == -1 else min(
                    remaining, self.local_batch_size)
                take = int(self._cap(take))
                sel = perms[cid][cursor[cid]:cursor[cid] + take]
                idx[w, :take] = sel
                mask[w, :take] = 1.0
                cursor[cid] += take
            if self.scheduler is not None:
                self.scheduler.commit_round(slot_ids, mask.sum(axis=1))
            yield RoundIndices(slot_ids.astype(np.int32), idx, mask)


class ValSampler:
    """Shards the validation set into fixed [S, valid_batch_size]
    blocks, padding the tail with masked examples (the val path of
    reference fed_aggregator.py:337-348 splits by valid_batch_size)."""

    def __init__(self, num_examples: int, valid_batch_size: int,
                 num_shards: int):
        self.n = num_examples
        self.vb = valid_batch_size
        self.num_shards = num_shards

    def batches(self) -> Iterator[RoundIndices]:
        per_super = self.vb * self.num_shards
        for start in range(0, self.n, per_super):
            idxs = np.arange(start, min(start + per_super, self.n))
            pad = per_super - len(idxs)
            mask = np.concatenate(
                [np.ones(len(idxs), np.float32), np.zeros(pad, np.float32)])
            idxs = np.concatenate([idxs, np.zeros(pad, np.int64)])
            yield RoundIndices(
                np.full(self.num_shards, -1, np.int32),
                idxs.reshape(self.num_shards, self.vb).astype(np.int32),
                mask.reshape(self.num_shards, self.vb))

"""Client sampling: which clients participate each round, with which
data — emitting *static-shape* padded batches.

Capability parity with the reference's FedSampler (reference:
CommEfficient/data_utils/fed_sampler.py:19-68): per epoch, permute data
within each client, then repeatedly draw `num_workers` non-exhausted
clients without replacement and take up to `local_batch_size` examples
from each (the whole remaining client dataset when -1).

TPU-first difference: the reference yields ragged index lists (variable
`actual_batch_sizes`, fed_sampler.py:55-62) and lets torch build
variable-size batches; XLA needs one compiled program, so every round
here is [num_workers, B] indices + an f32 validity mask, B fixed for
the whole run (SURVEY.md §7.3 hard part #2). Rounds with fewer than
num_workers non-exhausted clients end the epoch — the reference
*dispatches* such batches and then skips them in the driver
(cv_train.py:205-219), which is equivalent up to RNG state.
"""
from __future__ import annotations

from typing import Iterator, NamedTuple, Optional

import numpy as np


class RoundIndices(NamedTuple):
    client_ids: np.ndarray   # [num_workers] int32
    idx_within: np.ndarray   # [num_workers, B] int32 local indices
    mask: np.ndarray         # [num_workers, B] f32 validity


class FedSampler:
    def __init__(self, data_per_client: np.ndarray, num_workers: int,
                 local_batch_size: int, seed: int = 0,
                 shuffle_clients: bool = True,
                 max_local_batch: int = -1, scheduler=None):
        """max_local_batch caps the static batch dim B when
        local_batch_size == -1 (whole-client batches): a client with
        more data than the cap stays non-exhausted and participates in
        consecutive rounds on successive chunks. Bounds the
        [num_workers, B, ...] staging arrays that are otherwise sized
        by max(data_per_client) — the ImageNet-scale memory hazard.

        scheduler: optional RoundScheduler (commefficient_tpu/
        scheduler; also settable post-construction — drivers attach
        via scheduler.attach_round_scheduler). When set, participant
        selection is delegated to its policy; the UNIFORM default
        makes the byte-identical `rng.choice` call this class made
        before the scheduler existed, so the drawn stream — and
        everything downstream — is bit-unchanged. A policy may select
        FEWER than num_workers clients (over-provisioning targets);
        the surplus slots are padded with distinct UNCHOSEN client
        ids carrying all-zero masks — the scheduler marks them dead
        (survivor 0) so the jitted round leaves their state rows
        bit-untouched and accounting charges them nothing. Pad ids
        must be distinct from the chosen ids: the round engine's
        scatter-back writes every slot's row, and a duplicate
        alive/dead id pair would race the alive client's update."""
        self.data_per_client = np.asarray(data_per_client)
        self.num_clients = len(self.data_per_client)
        self.num_workers = num_workers
        self.local_batch_size = local_batch_size
        self.max_local_batch = max_local_batch
        self.rng = np.random.RandomState(seed)
        self.shuffle_clients = shuffle_clients
        self.scheduler = scheduler
        # checkpointable stream state (ISSUE 8 satellite — the named
        # PR-5 opening): `_epoch` mirrors the LIVE epoch generator's
        # cursor/permutations/position so state_dict() can capture a
        # suspended mid-epoch stream; `_pending` holds a restored
        # mid-epoch state the next epoch() call continues from instead
        # of re-drawing. Without this, a non-uniform (tracker-driven)
        # resume could only REPLAY the epoch head against the
        # checkpoint-time tracker, re-drawing different selections and
        # therefore feeding a different data stream than the
        # uninterrupted run.
        self._epoch: Optional[dict] = None
        self._pending: Optional[dict] = None
        # set by load_state_dict, consumed by resolve_resume: a
        # restored rng (even without a mid-epoch stream) makes any
        # head-replay skip wrong
        self._restored = False
        if num_workers > self.num_clients:
            raise ValueError(
                f"num_workers={num_workers} > num_clients={self.num_clients}")

    def _cap(self, take: np.ndarray | int):
        """Applies ONLY to whole-client (-1) batches, per the flag's
        documented contract; explicit local_batch_size is untouched."""
        if self.local_batch_size == -1 and self.max_local_batch > 0:
            return np.minimum(take, self.max_local_batch)
        return take

    @property
    def round_batch_size(self) -> int:
        """Static per-client batch dimension B."""
        if self.local_batch_size == -1:
            return int(self._cap(int(self.data_per_client.max())))
        return self.local_batch_size

    def steps_per_epoch(self) -> int:
        """(reference utils.py:315-321; capped whole-client batches
        count each client once per chunk)"""
        if self.local_batch_size == -1:
            if self.max_local_batch > 0:
                participations = int(np.ceil(
                    self.data_per_client / self.max_local_batch).sum())
                return max(participations // self.num_workers, 1)
            return int(self.num_clients // self.num_workers)
        total = int(self.data_per_client.sum())
        return int(np.ceil(total / (self.local_batch_size * self.num_workers)))

    def epoch(self) -> Iterator[RoundIndices]:
        B = self.round_batch_size
        dpc = self.data_per_client
        if self._pending is not None:
            # continue a checkpoint-restored mid-epoch stream: the
            # restored rng state already reflects every draw up to the
            # suspension point, so nothing is re-drawn
            st, self._pending = self._pending, None
            perms, cursor, pos = st["perms"], st["cursor"], st["pos"]
        else:
            # per-client permutation of local indices
            perms = [self.rng.permutation(n) for n in dpc]
            cursor = np.zeros(self.num_clients, dtype=int)
            pos = 0
        # instance mirror of the generator's locals: perms/cursor are
        # mutated in place below, so state_dict() sees the suspended
        # stream's exact position. Deliberately NOT cleared in a
        # finally block — an abandoned generator is cleared at GC
        # time, which would make state capture depend on collector
        # timing; exhaustion clears it, epoch() overwrites it.
        self._epoch = {"perms": perms, "cursor": cursor, "pos": pos}

        while True:
            alive = np.where(cursor < dpc)[0]
            if len(alive) < self.num_workers:
                self._epoch = None
                return
            if self.scheduler is not None:
                # policy selection (possibly < num_workers under an
                # over-provisioning target); the uniform default makes
                # the identical rng.choice call the branch below does
                chosen = np.asarray(self.scheduler.select(
                    alive, self.num_workers, self.rng))
            else:
                chosen = self.rng.choice(alive, self.num_workers,
                                         replace=False)
            if len(chosen) < self.num_workers:
                # idle-slot padding: distinct ids NOT chosen this
                # round (num_clients >= num_workers guarantees
                # enough), zero-mask rows, cursor untouched — the
                # scheduler's plan marks them survivor-0
                pad = np.setdiff1d(np.arange(self.num_clients),
                                   chosen)[:self.num_workers
                                           - len(chosen)]
                slot_ids = np.concatenate([chosen, pad])
            else:
                slot_ids = chosen
            idx = np.zeros((self.num_workers, B), np.int32)
            mask = np.zeros((self.num_workers, B), np.float32)
            for w, cid in enumerate(chosen):
                remaining = dpc[cid] - cursor[cid]
                take = remaining if self.local_batch_size == -1 else min(
                    remaining, self.local_batch_size)
                take = int(self._cap(take))
                sel = perms[cid][cursor[cid]:cursor[cid] + take]
                idx[w, :take] = sel
                mask[w, :take] = 1.0
                cursor[cid] += take
            if self.scheduler is not None:
                self.scheduler.commit_round(slot_ids, mask.sum(axis=1))
            self._epoch["pos"] += 1
            yield RoundIndices(slot_ids.astype(np.int32), idx, mask)

    # ---------------- checkpointable stream state ------------------------

    @property
    def resume_pending(self) -> bool:
        """True when a restored mid-epoch stream is waiting for the
        next epoch() call."""
        return self._pending is not None

    @property
    def pending_pos(self) -> Optional[int]:
        """Epoch-relative position (rounds already drawn) of the
        restored mid-epoch stream, or None without one. The drivers
        compare this against their own per-epoch round cap: a
        restored stream that already REACHED the cap was abandoned by
        the uninterrupted run at that exact point (driver stream
        wrappers cap, then abandon_epoch), so the resume must discard
        it (discard_pending) and open a fresh epoch instead —
        and a stream short of the cap must only be driven for the
        REMAINING cap - pos rounds."""
        return (None if self._pending is None
                else int(self._pending["pos"]))

    def discard_pending(self) -> None:
        """Drop a restored mid-epoch stream (see pending_pos): the
        next epoch() call draws fresh permutations from the restored
        rng — which already includes every draw of the abandoned
        stream, so the fresh epoch matches the uninterrupted run's."""
        self._pending = None

    def abandon_epoch(self) -> None:
        """Driver hook: the epoch's stream is logically OVER even
        though the generator never exhausted (the drivers' per-epoch
        round caps end epochs by abandoning the stream, after a
        pull-then-discard). Clears the live-stream mirror so a
        checkpoint written after this point records in_epoch=0 — a
        resume then opens a fresh epoch from the restored rng, exactly
        what the uninterrupted run does. The rng itself is untouched:
        it must keep the abandoned stream's draws (the uninterrupted
        timeline made them too). Callers MUST invoke this before any
        checkpoint that follows the abandonment (the drivers' stream
        wrappers do, ahead of the scanned tail flush)."""
        self._epoch = None

    def resolve_resume(self, skip_rounds: int) -> int:
        """Driver hook at resume time: returns the `epoch(skip=)`
        value to use for the first resumed epoch.

        Whenever THIS run restored sampler state (load_state_dict),
        the answer is 0 — the restored rng/cursor already encode the
        stream position exactly, so any skip would throw away rounds
        the uninterrupted run trains (the old spe-modulus fast-forward
        mis-skips whenever real epoch length drifts from the
        steps_per_epoch estimate — exhaustion-ended epochs, capped
        whole-client batches). Whether the next epoch() call continues
        a mid-epoch stream or opens a fresh one is decided by the
        CHECKPOINT (in_epoch — the drivers mark stream abandonment via
        abandon_epoch before checkpointing, so a saved live stream is
        genuinely live), never inferred from skip_rounds. Without
        restored state this is the identity: legacy checkpoints keep
        the replay fast-forward path."""
        if not self._restored:
            return int(skip_rounds)
        self._restored = False
        return 0

    def state_dict(self) -> dict:
        """Bit-exact serializable stream state: the MT19937 generator
        plus — when an epoch stream is live — its per-client
        permutations, cursors and position. All plain numpy arrays
        (checkpoint .npz friendly, `smp_*` keys)."""
        kind, key, pos, has_gauss, cached = self.rng.get_state()
        assert kind == "MT19937"
        out = {
            "rng_key": np.asarray(key, np.uint32),
            "rng_pos": np.int64(pos),
            "rng_has_gauss": np.int64(has_gauss),
            "rng_cached": np.float64(cached),
            "in_epoch": np.int64(0),
        }
        st = self._epoch if self._epoch is not None else self._pending
        if st is not None:
            out["in_epoch"] = np.int64(1)
            out["epoch_pos"] = np.int64(st["pos"])
            # COPY, not view: the live epoch mutates `cursor` in place
            # on every draw, and the pipelined span checkpoint
            # (ISSUE 10/12) persists this capture ONE SPAN LATE — an
            # aliased cursor would be silently advanced by the next
            # span's draws before it hits disk, desyncing every
            # pipelined resume (caught by test_controlplane's
            # pipelined coordinator-crash drill)
            out["cursor"] = np.array(st["cursor"], np.int64, copy=True)
            out["perm_flat"] = (
                np.concatenate([np.asarray(p, np.int64)
                                for p in st["perms"]])
                if len(st["perms"]) else np.zeros((0,), np.int64))
        return out

    def load_state_dict(self, state: dict) -> None:
        """Restore a state_dict() capture. Mid-epoch state parks in
        `_pending`; the next epoch() call continues the stream from
        the restored cursor instead of drawing fresh permutations."""
        self.rng.set_state((
            "MT19937", np.asarray(state["rng_key"], np.uint32),
            int(np.asarray(state["rng_pos"])),
            int(np.asarray(state["rng_has_gauss"])),
            float(np.asarray(state["rng_cached"]))))
        self._epoch = None
        self._pending = None
        self._restored = True
        if not int(np.asarray(state.get("in_epoch", 0))):
            return
        cursor = np.asarray(state["cursor"], dtype=int)
        flat = np.asarray(state["perm_flat"], dtype=int)
        dpc = self.data_per_client
        if cursor.shape[0] != self.num_clients or \
                flat.shape[0] != int(dpc.sum()):
            raise ValueError(
                "sampler checkpoint does not match this dataset: "
                f"cursor for {cursor.shape[0]} clients / "
                f"{flat.shape[0]} permutation entries vs "
                f"{self.num_clients} clients / {int(dpc.sum())} "
                "examples")
        perms, off = [], 0
        for n in dpc:
            perms.append(flat[off:off + int(n)].copy())
            off += int(n)
        self._pending = {"perms": perms, "cursor": cursor.copy(),
                         "pos": int(np.asarray(state["epoch_pos"]))}


class ValSampler:
    """Shards the validation set into fixed [S, valid_batch_size]
    blocks, padding the tail with masked examples (the val path of
    reference fed_aggregator.py:337-348 splits by valid_batch_size)."""

    def __init__(self, num_examples: int, valid_batch_size: int,
                 num_shards: int):
        self.n = num_examples
        self.vb = valid_batch_size
        self.num_shards = num_shards

    def batches(self) -> Iterator[RoundIndices]:
        per_super = self.vb * self.num_shards
        for start in range(0, self.n, per_super):
            idxs = np.arange(start, min(start + per_super, self.n))
            pad = per_super - len(idxs)
            mask = np.concatenate(
                [np.ones(len(idxs), np.float32), np.zeros(pad, np.float32)])
            idxs = np.concatenate([idxs, np.zeros(pad, np.int64)])
            yield RoundIndices(
                np.full(self.num_shards, -1, np.int32),
                idxs.reshape(self.num_shards, self.vb).astype(np.int32),
                mask.reshape(self.num_shards, self.vb))

"""Federated ImageNet: one client per wnid class.

Capability parity with the reference (reference:
CommEfficient/data_utils/fed_imagenet.py — wraps an already-downloaded
torchvision ImageNet, refuses to download :15-16,22-23, one class per
client, and generates only stats.json :44-64). Same stance here: the
dataset must already be on disk; `prepare` only indexes it.

Accepted layouts under <dataset_dir>/ImageNet/:
  1. preprocessed/: one `client<i>.npy` per class ([n, H, W, 3] uint8)
     + `val.npz` (images, labels) — the fast path; produce it once
     with any offline resize job.
  2. raw/train/<wnid>/*.JPEG + raw/val/<wnid>/*.JPEG — indexed lazily;
     images are decoded and resized on fetch (PIL), one class-file
     cache at a time.
  3. `synthetic_examples=(n_train, n_val)` smoke fallback.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.utils.atomic_io import atomic_save, atomic_savez

NUM_CLASSES = 1000

# bump when _generate_synthetic's semantics change: the on-disk cache
# is keyed by geometry + this stamp (see _cached_stats_ok)
_SYNTH_VERSION = 1


class FedImageNet(FedDataset):
    num_classes = NUM_CLASSES

    def __init__(self, dataset_dir, dataset_name="ImageNet", transform=None,
                 do_iid=False, num_clients=None, train=True, download=False,
                 synthetic_examples: Optional[Tuple[int, int]] = None,
                 image_size: int = 224, seed: int = 0):
        self._synthetic_examples = synthetic_examples
        self._seed = seed
        self._size = image_size
        self._cache = {}
        self._wnid_files = None
        super().__init__(dataset_dir, dataset_name, transform, do_iid,
                         num_clients, train, download, seed)

    def _dir(self):
        return os.path.join(self.dataset_dir, self.dataset_name)

    def _pre(self, name):
        return os.path.join(self._dir(), "preprocessed", name)

    def _cached_stats_ok(self) -> bool:
        """Re-prepare when the cached synthetic corpus isn't the
        sizing asked for (see FedDataset._cached_stats_ok); real
        preprocessed/raw layouts on disk always win."""
        if self._synthetic_examples is None:
            return True
        raw = os.path.join(self._dir(), "raw", "train")
        # a preprocessed/ dir NOT written by _generate_synthetic is a
        # real layout; the synthetic one is identified by its stats
        # matching the deterministic generator geometry below
        if os.path.isdir(raw):
            return True
        try:
            import json
            with open(self.stats_path()) as f:
                stats = json.load(f)
        except (OSError, ValueError):
            # missing/unreadable/torn stats file -> re-prepare; anything
            # else (incl. InjectedFault from the fault harness) raises
            return False
        n_train, n_val = self._synthetic_examples
        n_cls = min(NUM_CLASSES, 16)
        per = max(n_train // n_cls, 1)
        ipc = stats["images_per_client"]
        return (stats.get("source", "synthetic") == "synthetic"
                and stats.get("synthetic_version",
                              _SYNTH_VERSION) == _SYNTH_VERSION
                and len(ipc) == n_cls and all(n == per for n in ipc)
                and stats["num_val_images"] == n_val)

    # ---- indexing -------------------------------------------------------
    def prepare(self, download: bool = False):
        if download:
            raise RuntimeError(
                "ImageNet cannot be downloaded automatically (reference "
                "fed_imagenet.py:15-16 takes the same stance)")
        pre = os.path.join(self._dir(), "preprocessed")
        raw = os.path.join(self._dir(), "raw", "train")
        if os.path.isdir(pre):
            counts = []
            for c in range(NUM_CLASSES):
                p = self._pre(f"client{c}.npy")
                if not os.path.exists(p):
                    break
                counts.append(len(np.load(p, mmap_mode="r")))
            n_val = len(np.load(self._pre("val.npz"))["labels"]) \
                if os.path.exists(self._pre("val.npz")) else 0
            self.write_stats(counts, n_val,
                             extra={"source": "preprocessed"})
        elif os.path.isdir(raw):
            wnids = sorted(os.listdir(raw))
            counts = [len(os.listdir(os.path.join(raw, w))) for w in wnids]
            val_dir = os.path.join(self._dir(), "raw", "val")
            n_val = (sum(len(os.listdir(os.path.join(val_dir, w)))
                         for w in os.listdir(val_dir))
                     if os.path.isdir(val_dir) else 0)
            self.write_stats(counts, n_val, extra={"source": "raw"})
        elif self._synthetic_examples is not None:
            n_train, n_val = self._synthetic_examples
            self._generate_synthetic(n_train, n_val)
        else:
            raise FileNotFoundError(
                f"No ImageNet under {self._dir()} (expected preprocessed/ "
                f"or raw/train/<wnid>/); pass synthetic_examples for a "
                f"smoke corpus")

    def _generate_synthetic(self, n_train: int, n_val: int):
        rng = np.random.RandomState(self._seed)
        hw = min(self._size, 64)  # keep the smoke corpus small
        n_cls = min(NUM_CLASSES, 16)
        per = max(n_train // n_cls, 1)
        os.makedirs(self._pre(""), exist_ok=True)
        counts = []
        templates = rng.rand(n_cls, hw, hw, 3).astype(np.float32)
        for c in range(n_cls):
            x = np.clip(templates[c] + rng.randn(per, hw, hw, 3) * 0.1,
                        0, 1)
            atomic_save(self._pre(f"client{c}.npy"),
                        (x * 255).astype(np.uint8))
            counts.append(per)
        yv = rng.randint(0, n_cls, n_val)
        xv = np.clip(templates[yv] + rng.randn(n_val, hw, hw, 3) * 0.1, 0, 1)
        atomic_savez(self._pre("val.npz"),
                     images=(xv * 255).astype(np.uint8), labels=yv)
        self.write_stats(counts, n_val,
                         extra={"source": "synthetic",
                                "synthetic_version": _SYNTH_VERSION})

    # ---- fetch ----------------------------------------------------------
    def _raw_class_images(self, cid: int) -> np.ndarray:
        from PIL import Image
        raw = os.path.join(self._dir(), "raw", "train")
        if self._wnid_files is None:
            wnids = sorted(os.listdir(raw))
            self._wnid_files = [
                (w, sorted(os.listdir(os.path.join(raw, w))))
                for w in wnids]
        wnid, files = self._wnid_files[cid]
        out = np.zeros((len(files), self._size, self._size, 3), np.uint8)
        for i, f in enumerate(files):
            img = Image.open(os.path.join(raw, wnid, f)).convert("RGB")
            out[i] = np.asarray(
                img.resize((self._size, self._size)), np.uint8)
        return out

    def _class_images(self, cid: int) -> np.ndarray:
        if cid not in self._cache:
            p = self._pre(f"client{cid}.npy")
            if os.path.exists(p):
                arr = np.load(p, mmap_mode="r")
            else:
                arr = self._raw_class_images(cid)
            # bounded cache: one class-file at a time (classes are
            # visited in sampler blocks, so locality is high)
            self._cache = {k: v for k, v in self._cache.items()
                           if k == "val"}
            self._cache[cid] = arr
        return self._cache[cid]

    def _get_train_batch(self, nat_client_id: int, idxs: np.ndarray):
        imgs = self._class_images(nat_client_id)[np.asarray(idxs)]
        labels = np.full(len(idxs), nat_client_id, np.int64)
        return np.asarray(imgs), labels

    def _get_val_batch(self, idxs: np.ndarray):
        if "val" not in self._cache:
            z = np.load(self._pre("val.npz"))
            self._cache["val"] = (z["images"], z["labels"])
        imgs, labels = self._cache["val"]
        return imgs[idxs], labels[idxs]

"""Federated PersonaChat: one client per personality tuple.

Capability parity with the reference's PERSONA layer (reference:
CommEfficient/data_utils/fed_persona.py): dialog partitioning by
persona tuple (:144-147), nested utterance->dialog->client index math
(:195-215), segment building with <bos>/<eos>/<speaker1>/<speaker2>
special tokens (:330-358), last-candidate-is-correct multiple choice
(:304), and the batch x num_candidates x seq_len collate (:360-392).

TPU-first re-design:
  * The reference tokenizes and builds segments lazily per __getitem__,
    re-reading the client's JSON file from disk every time
    (fed_persona.py:218-222) and pads per-batch to the batch max
    length. Here the whole corpus is tokenized ONCE at prepare time
    into memory-mapped .npz arrays padded to the corpus-wide max
    sequence length — static shapes end to end (one compiled program),
    and fetches are pure numpy slices.
  * `personality_permutations` emits each utterance P times with
    deterministic persona-order rotations at prepare time, growing the
    corpus x P. (The reference shuffles in __getitem__ but returns only
    the last permutation — drift, not replicated; see fed_persona.py:
    231-236 where `model_inputs.extend` is dead code.)

Tokenization is injectable: `transformers`' GPT2 BPE is used when a
local cache exists; otherwise `HashTokenizer` provides a deterministic
offline vocabulary (and is what the synthetic corpus/tests use).

An example is (input_ids [C, L], mc_token_ids [C], lm_labels [C, L],
mc_labels scalar, token_type_ids [C, L]) — the reference MODEL_INPUTS
order (fed_persona.py:27-28). lm_labels use -1 as ignore (reference
nll ignore_index, gpt2_train.py:78).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.utils.atomic_io import atomic_savez

SPECIAL_TOKENS = ("<bos>", "<eos>", "<speaker1>", "<speaker2>", "<pad>")
IGNORE_INDEX = -1


class HashTokenizer:
    """Deterministic offline word-level tokenizer: words hash into
    [num_special, vocab_size); the 5 PersonaChat special tokens take
    ids 0..4. Stands in for GPT2 BPE in zero-egress environments."""

    def __init__(self, vocab_size: int = 1000):
        assert vocab_size > len(SPECIAL_TOKENS) + 1
        self.vocab_size = vocab_size
        self._special = {t: i for i, t in enumerate(SPECIAL_TOKENS)}

    def __len__(self):
        return self.vocab_size

    def tokenize(self, text: str) -> List[int]:
        out = []
        for w in text.lower().split():
            h = int(hashlib.md5(w.encode()).hexdigest(), 16)
            n = len(self._special)
            out.append(n + h % (self.vocab_size - n))
        return out

    def special_ids(self) -> Dict[str, int]:
        return dict(self._special)


class GPT2BPETokenizer:
    """transformers GPT2 BPE with PersonaChat special tokens appended
    (the reference adds them the same way, gpt2_train.py:26-32,226-232).
    Requires a local HF cache — raises if none exists."""

    def __init__(self, model_checkpoint: str = "gpt2"):
        from transformers import GPT2Tokenizer
        self.tok = GPT2Tokenizer.from_pretrained(
            model_checkpoint, local_files_only=True)
        self.base_vocab = len(self.tok)
        self.tok.add_special_tokens({
            "bos_token": "<bos>", "eos_token": "<eos>",
            "pad_token": "<pad>",
            "additional_special_tokens": ["<speaker1>", "<speaker2>"]})

    def __len__(self):
        return len(self.tok)

    def tokenize(self, text: str) -> List[int]:
        return self.tok.convert_tokens_to_ids(self.tok.tokenize(text))

    def special_ids(self) -> Dict[str, int]:
        ids = self.tok.convert_tokens_to_ids(list(SPECIAL_TOKENS))
        return dict(zip(SPECIAL_TOKENS, ids))


def make_tokenizer(model_checkpoint: str = "gpt2",
                   fallback_vocab: int = 1000):
    """GPT2 BPE when locally cached, HashTokenizer otherwise."""
    try:
        return GPT2BPETokenizer(model_checkpoint)
    except (ImportError, OSError, ValueError, RuntimeError, TypeError):
        # transformers missing / no locally-cached vocab files / torn
        # cache — the expected offline failure modes. TypeError is on
        # the list because transformers resolves missing cached vocab
        # files to None and dies in open(None). Anything else (incl.
        # InjectedFault from the fault harness) raises.
        return HashTokenizer(fallback_vocab)


class _MemoTokenizer:
    """String->tokens memo held for the duration of prepare(): persona
    sentences recur once per utterance x permutation and history turns
    once per subsequent utterance, so caching cuts BPE work several-
    fold on the real corpus."""

    def __init__(self, tok):
        self._tok = tok
        self._cache: Dict[str, List[int]] = {}

    def __len__(self):
        return len(self._tok)

    def tokenize(self, text: str) -> List[int]:
        got = self._cache.get(text)
        if got is None:
            got = self._cache[text] = self._tok.tokenize(text)
        return got

    def special_ids(self) -> Dict[str, int]:
        return self._tok.special_ids()


# ---- segment building (reference build_input_from_segments,
#      fed_persona.py:330-358) --------------------------------------------

def build_input_from_segments(persona: Sequence[Sequence[int]],
                              history: Sequence[Sequence[int]],
                              reply: Sequence[int],
                              special: Dict[str, int],
                              lm_labels: bool = False,
                              with_eos: bool = True) -> Dict[str, list]:
    """Assemble one candidate sequence from tokenized segments:
    [<bos> persona*] [<spk> turn]... [<spk2> reply <eos>], with
    per-segment token types and LM labels only on the reply tokens of
    the correct candidate. Formula-identical to the reference (the
    segment grammar IS the dataset contract)."""
    bos, eos = special["<bos>"], special["<eos>"]
    spk1, spk2 = special["<speaker1>"], special["<speaker2>"]

    persona_flat = [t for seg in persona for t in seg]
    segments = [[bos] + persona_flat] + [list(h) for h in history]
    segments += [list(reply) + ([eos] if with_eos else [])]
    # prepend alternating speaker tokens; the reply always gets
    # <speaker2>. NB: with odd-length history (the real-PersonaChat
    # case) the prepended speaker and the segment's token_type disagree
    # — that quirk is the reference's exact formula
    # (fed_persona.py:343-347 uses `% 2 == 0`, diverging from upstream
    # HF convai's `% 2`), kept verbatim for dataset-level parity.
    n = len(segments)
    segments = [segments[0]] + [
        [spk2 if (n - i) % 2 == 0 else spk1] + seg
        for i, seg in enumerate(segments[1:])]

    input_ids = [t for seg in segments for t in seg]
    token_type_ids = [spk2 if i % 2 else spk1
                      for i, seg in enumerate(segments) for _ in seg]
    out = {
        "input_ids": input_ids,
        "token_type_ids": token_type_ids,
        "mc_token_ids": len(input_ids) - 1,
        "lm_labels": [IGNORE_INDEX] * len(input_ids),
    }
    if lm_labels:
        prefix = sum(len(s) for s in segments[:-1])
        out["lm_labels"] = ([IGNORE_INDEX] * prefix
                            + [IGNORE_INDEX] + segments[-1][1:])
    return out


def utterance_to_arrays(persona, history, candidates, tokenizer,
                        num_candidates: int, max_history: int,
                        seq_len: Optional[int] = None):
    """One utterance -> padded candidate arrays. The LAST candidate is
    the ground truth (reference fed_persona.py:304). Truncates history
    to the last 2*max_history+1 turns and candidates to the last
    num_candidates (reference :249-255). Returns
    (input_ids [C, L], mc_token_ids [C], lm_labels [C, L],
     mc_label scalar, token_type_ids [C, L]) with L = seq_len (or the
    utterance max when None)."""
    special = tokenizer.special_ids()
    if num_candidates > 0:
        candidates = candidates[-num_candidates:]
    history = history[-(2 * max_history + 1):]

    tp = [tokenizer.tokenize(p) for p in persona]
    th = [tokenizer.tokenize(h) for h in history]
    tc = [tokenizer.tokenize(c) for c in candidates]

    instances = [
        build_input_from_segments(tp, th, cand, special,
                                  lm_labels=(j == len(tc) - 1))
        for j, cand in enumerate(tc)]

    L = seq_len or max(len(inst["input_ids"]) for inst in instances)
    C = len(instances)
    pad = special["<pad>"]
    input_ids = np.full((C, L), pad, np.int32)
    token_type = np.full((C, L), pad, np.int32)
    labels = np.full((C, L), IGNORE_INDEX, np.int32)
    mc_token_ids = np.zeros((C,), np.int32)
    for j, inst in enumerate(instances):
        ln = min(len(inst["input_ids"]), L)
        input_ids[j, :ln] = inst["input_ids"][:ln]
        token_type[j, :ln] = inst["token_type_ids"][:ln]
        labels[j, :ln] = inst["lm_labels"][:ln]
        mc_token_ids[j] = min(inst["mc_token_ids"], L - 1)
    return input_ids, mc_token_ids, labels, np.int32(C - 1), token_type


def _synthetic_personachat(num_personas: int, dialogs_per_persona: int,
                           utterances_per_dialog: int,
                           num_candidates: int, seed: int) -> dict:
    """Deterministic synthetic corpus in the raw personachat JSON
    schema, for zero-egress environments (mirrors the CIFAR/EMNIST
    synthetic-fallback pattern)."""
    rng = np.random.RandomState(seed)
    words = [f"w{i}" for i in range(200)]

    def sent(n):
        return " ".join(rng.choice(words, size=n))

    personas = {}

    def persona_of(pid):
        if pid not in personas:
            personas[pid] = [f"persona {pid} trait {t} " + sent(3)
                             for t in range(4)]
        return personas[pid]

    def dialog(pid):
        persona = persona_of(pid)
        utts = []
        history = [sent(5)]
        for _ in range(utterances_per_dialog):
            cands = [sent(rng.randint(3, 8)) for _ in range(num_candidates)]
            utts.append({"history": list(history),
                         "candidates": cands})
            history.append(cands[-1])
            history.append(sent(5))
        return {"personality": persona, "utterances": utts}

    train = [dialog(p) for p in range(num_personas)
             for _ in range(dialogs_per_persona)]
    valid = [dialog(10_000 + p) for p in range(max(2, num_personas // 4))]
    return {"train": train, "valid": valid}


class FedPERSONA(FedDataset):
    """Persona-partitioned PersonaChat with prepare-time tokenization.

    Storage layout under <dataset_dir>/PERSONA/:
      raw .json           — personachat_self_original.json (if present)
      train_<key>.npz     — input_ids/token_type_ids/lm_labels
                            [N, C, L] int32, mc_token_ids [N, C],
                            mc_labels [N] (+ client offsets)
      val_<key>.npz       — same arrays for the validation dialogs
      stats.json          — utterances per client + val count + seq_len
    where <key> encodes (num_candidates, max_history,
    personality_permutations) so differently-configured runs don't
    collide."""

    RAW_NAME = "personachat_self_original.json"

    def __init__(self, dataset_dir, dataset_name="PERSONA", tokenizer=None,
                 num_candidates: int = 2, max_history: int = 2,
                 personality_permutations: int = 1,
                 transform=None, do_iid=False, num_clients=None,
                 train=True, download=False,
                 synthetic_examples: Optional[Tuple[int, int, int]] = None,
                 seed: int = 0):
        self.tokenizer = tokenizer or make_tokenizer()
        self.num_candidates = num_candidates
        self.max_history = max_history
        self.personality_permutations = personality_permutations
        self._synthetic_examples = synthetic_examples
        self._seed = seed
        self._z: dict = {}
        super().__init__(dataset_dir, dataset_name, transform, do_iid,
                         num_clients, train, download, seed)

    # ---- paths ----------------------------------------------------------
    def _dir(self):
        return os.path.join(self.dataset_dir, self.dataset_name)

    def _key(self):
        # the cache key must pin the tokenizer identity: ids from a
        # different tokenizer/vocab are silently wrong if reused
        tok = f"{type(self.tokenizer).__name__}{len(self.tokenizer)}"
        syn = ("" if self._synthetic_examples is None
               else "_s" + "x".join(map(str, self._synthetic_examples)))
        return (f"c{self.num_candidates}_h{self.max_history}"
                f"_p{self.personality_permutations}_{tok}{syn}")

    def _npz_path(self, split: str) -> str:
        return os.path.join(self._dir(), f"{split}_{self._key()}.npz")

    def stats_path(self) -> str:
        return os.path.join(self._dir(), f"stats_{self._key()}.json")

    # ---- preparation ----------------------------------------------------
    def prepare(self, download: bool = False):
        raw_path = os.path.join(self._dir(), self.RAW_NAME)
        if os.path.exists(raw_path):
            with open(raw_path) as f:
                raw = json.load(f)
        elif self._synthetic_examples is not None:
            n_personas, dpp, upd = self._synthetic_examples
            raw = _synthetic_personachat(
                n_personas, dpp, upd, max(self.num_candidates, 2),
                self._seed)
        else:
            raise FileNotFoundError(
                f"No {self.RAW_NAME} under {self._dir()} and no network "
                f"egress; pass synthetic_examples=(num_personas, "
                f"dialogs_per_persona, utterances_per_dialog)")

        # partition train dialogs by persona tuple (reference :144-147)
        clients: Dict[tuple, list] = {}
        for dialog in raw["train"]:
            clients.setdefault(tuple(dialog["personality"]), []).append(
                dialog)

        os.makedirs(self._dir(), exist_ok=True)
        counts = self._write_split(
            "train", [d for ds in clients.values() for d in ds],
            per_client_dialogs=[len(ds) for ds in clients.values()],
            train=True)
        n_val = self._write_split("val", raw["valid"], None, train=False)
        self.write_stats(counts, n_val)

    def _examples_of(self, dialog, train: bool):
        """Yield (persona_rotation, history, candidates) tuples for
        every utterance, applying persona rotations for train."""
        persona = list(dialog["personality"])
        perms = self.personality_permutations if train else 1
        for utt in dialog["utterances"]:
            for p in range(perms):
                rot = persona[p % len(persona):] + persona[:p % len(persona)]
                yield rot, utt["history"], utt["candidates"]

    def _write_split(self, split: str, dialogs: list,
                     per_client_dialogs: Optional[List[int]], train: bool):
        examples = []
        for dialog in dialogs:
            for ex in self._examples_of(dialog, train):
                examples.append(ex)

        # two passes over a streamed build: pass 1 finds the corpus
        # (C, L) envelope, pass 2 fills the preallocated block directly
        # — per-utterance arrays are never held all at once (the memo
        # makes the second tokenization pass nearly free)
        ncand = self.num_candidates if train else 0  # val keeps all
        memo = _MemoTokenizer(self.tokenizer)

        def stream():
            for p, h, c in examples:
                yield utterance_to_arrays(p, h, c, memo, ncand,
                                          self.max_history)

        C = L = 1
        for arrs in stream():
            C = max(C, int(arrs[0].shape[0]))
            L = max(L, int(arrs[0].shape[1]))

        N = len(examples)
        pad = self.tokenizer.special_ids()["<pad>"]
        input_ids = np.full((N, C, L), pad, np.int32)
        token_type = np.full((N, C, L), pad, np.int32)
        labels = np.full((N, C, L), IGNORE_INDEX, np.int32)
        mc_token_ids = np.zeros((N, C), np.int32)
        mc_labels = np.zeros((N,), np.int32)
        for i, arrs in enumerate(stream()):
            ii, mt, lb, ml, tt = arrs
            c, l = ii.shape
            input_ids[i, :c, :l] = ii
            token_type[i, :c, :l] = tt
            labels[i, :c, :l] = lb
            mc_token_ids[i, :c] = mt
            mc_labels[i] = ml

        arrays = dict(input_ids=input_ids, mc_token_ids=mc_token_ids,
                      lm_labels=labels, mc_labels=mc_labels,
                      token_type_ids=token_type)
        if train:
            # utterances per client = dialog utterance counts x perms
            counts, start = [], 0
            for nd in per_client_dialogs:
                n_utt = sum(
                    len(d["utterances"]) * self.personality_permutations
                    for d in dialogs[start:start + nd])
                counts.append(n_utt)
                start += nd
            arrays["offsets"] = np.concatenate([[0], np.cumsum(counts)])
            atomic_savez(self._npz_path(split), **arrays)
            return counts
        atomic_savez(self._npz_path(split), **arrays)
        return N

    # ---- fetch ----------------------------------------------------------
    def _load(self, split: str):
        if split not in self._z:
            self._z[split] = np.load(self._npz_path(split), mmap_mode="r")
        return self._z[split]

    def _batch_from(self, z, sel: np.ndarray):
        return (np.asarray(z["input_ids"][sel]),
                np.asarray(z["mc_token_ids"][sel]),
                np.asarray(z["lm_labels"][sel]),
                np.asarray(z["mc_labels"][sel]),
                np.asarray(z["token_type_ids"][sel]))

    def _get_train_batch(self, nat_client_id: int, idxs: np.ndarray):
        z = self._load("train")
        sel = z["offsets"][nat_client_id] + np.asarray(idxs)
        return self._batch_from(z, sel)

    def _get_val_batch(self, idxs: np.ndarray):
        return self._batch_from(self._load("val"), np.asarray(idxs))

    @property
    def seq_len(self) -> int:
        return int(self._load("train" if self.train else "val")
                   ["input_ids"].shape[-1])

    @property
    def vocab_size(self) -> int:
        return len(self.tokenizer)

"""Federated CIFAR10/CIFAR100.

Capability parity with the reference (reference:
CommEfficient/data_utils/fed_cifar.py): the train set is partitioned
into one natural unit per class — label == natural client id
(reference fed_cifar.py:77-84) — and resharded over `num_clients` by
FedDataset.data_per_client; the val set is flat.

Sources, in order of preference:
  1. the standard CIFAR python pickle batches under dataset_dir
     (cifar-10-batches-py / cifar-100-python), if present on disk;
  2. a deterministic synthetic substitute (class-dependent Gaussian
     blobs) sized by `synthetic_examples` — this environment has no
     network egress, and tests/benchmarks need data with the real
     shapes and a learnable class signal.

Storage: one .npy per class (the reference's layout choice,
fed_cifar.py:45-58) under <dataset_dir>/<name>/.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Tuple

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.utils.atomic_io import atomic_save, atomic_savez


def _try_load_cifar_pickles(root: str, name: str):
    """Read the standard CIFAR batch pickles if present."""
    if name == "CIFAR10":
        d = os.path.join(root, "cifar-10-batches-py")
        if not os.path.isdir(d):
            return None
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(d, f"data_batch_{i}"), "rb") as f:
                b = pickle.load(f, encoding="bytes")
            xs.append(b[b"data"])
            ys.extend(b[b"labels"])
        with open(os.path.join(d, "test_batch"), "rb") as f:
            tb = pickle.load(f, encoding="bytes")
        train = (np.concatenate(xs), np.array(ys))
        test = (np.asarray(tb[b"data"]), np.array(tb[b"labels"]))
    else:
        d = os.path.join(root, "cifar-100-python")
        if not os.path.isdir(d):
            return None
        with open(os.path.join(d, "train"), "rb") as f:
            b = pickle.load(f, encoding="bytes")
        train = (np.asarray(b[b"data"]), np.array(b[b"fine_labels"]))
        with open(os.path.join(d, "test"), "rb") as f:
            b = pickle.load(f, encoding="bytes")
        test = (np.asarray(b[b"data"]), np.array(b[b"fine_labels"]))

    def to_nhwc(x):
        return x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)

    return (to_nhwc(train[0]), train[1]), (to_nhwc(test[0]), test[1])


# bump when the generator's semantics change: the on-disk .npy cache
# is keyed by example counts only, so a semantic change must force a
# re-prepare (see _cached_stats_ok)
_SYNTH_VERSION = 2


def _synthetic_cifar(num_classes: int, n_train: int, n_val: int, seed: int,
                     signal: float = 0.6):
    """Deterministic class-separable images: per-class mean pattern +
    noise. Gives smoke/bench runs a learnable signal.

    v2: the class protos are LOW-FREQUENCY (8x8 blocks upsampled to
    32x32) and horizontally symmetric. v1 used i.i.d. per-pixel
    protos, which the standard train transforms destroy: a +-4px
    random crop decorrelates a per-pixel pattern almost entirely and
    a horizontal flip negates it, so even direct SGD sat at chance
    for epochs (measured — PERF.md round 5 / benchmarks/c3_probe.py).
    Blocky symmetric protos survive crop (75%+ block overlap) and
    flip (exactly invariant), making the augmented synthetic task
    behave like real CIFAR instead of an adversarial one.

    `signal` is the proto mixing weight (1-signal is noise): 0.6 makes
    an easy corpus for smokes/benches; convergence studies that need
    the compression modes to DIFFERENTIATE (not all saturate at 1.0)
    pass a lower value."""
    rng = np.random.RandomState(seed)
    base = rng.rand(num_classes, 8, 8, 3).astype(np.float32)
    base = (base + base[:, :, ::-1]) / 2            # flip-invariant
    protos = np.repeat(np.repeat(base, 4, axis=1), 4, axis=2)

    def gen(n):
        labels = rng.randint(0, num_classes, size=n)
        noise = rng.rand(n, 32, 32, 3).astype(np.float32)
        imgs = signal * protos[labels] + (1.0 - signal) * noise
        return (imgs * 255).astype(np.uint8), labels.astype(np.int64)

    return gen(n_train), gen(n_val)


class FedCIFAR10(FedDataset):
    num_classes = 10

    def __init__(self, dataset_dir, dataset_name="CIFAR10", transform=None,
                 do_iid=False, num_clients=None, train=True, download=False,
                 synthetic_examples: Optional[Tuple[int, int]] = None,
                 seed: int = 0, synthetic_signal: float = 0.6):
        self._synthetic_examples = synthetic_examples
        self._synthetic_signal = synthetic_signal
        self._seed = seed
        super().__init__(dataset_dir, dataset_name, transform, do_iid,
                         num_clients, train, download, seed)
        self._cache = {}

    def _dir(self):
        return os.path.join(self.dataset_dir, self.dataset_name)

    def _cached_stats_ok(self) -> bool:
        """Re-prepare when the cached corpus isn't the one that would
        be prepared NOW: real pickle archives on disk always win (so a
        cache stamped source=synthetic is stale the moment pickles
        appear), and a synthetic cache must match both the requested
        sizing and the current generator version."""
        try:
            import json
            with open(self.stats_path()) as f:
                stats = json.load(f)
        except (OSError, ValueError):
            # missing/unreadable/torn stats file -> re-prepare; anything
            # else (incl. InjectedFault from the fault harness) raises
            return False
        have_pickles = _try_load_cifar_pickles(
            self.dataset_dir, self.dataset_name) is not None
        if have_pickles:
            return stats.get("source") == "pickles"
        if self._synthetic_examples is None:
            # no pickles and nothing to generate: let prepare() raise
            # its actionable FileNotFoundError only if the cache is
            # absent; an existing cache (whatever its source) is all
            # there is
            return True
        n_train, n_val = self._synthetic_examples
        return (stats.get("source") == "synthetic"
                and sum(stats["images_per_client"]) == n_train
                and stats["num_val_images"] == n_val
                and stats.get("synthetic_version") == _SYNTH_VERSION
                and stats.get("synthetic_signal") == self._synthetic_signal)

    def prepare(self, download: bool = False):
        loaded = _try_load_cifar_pickles(self.dataset_dir,
                                         self.dataset_name)
        if loaded is None:
            if self._synthetic_examples is None:
                raise FileNotFoundError(
                    f"No {self.dataset_name} archives under "
                    f"{self.dataset_dir} and no network egress; pass "
                    f"synthetic_examples=(n_train, n_val) to generate "
                    f"synthetic data")
            n_train, n_val = self._synthetic_examples
            (xtr, ytr), (xva, yva) = _synthetic_cifar(
                self.num_classes, n_train, n_val, self._seed,
                signal=self._synthetic_signal)
        else:
            (xtr, ytr), (xva, yva) = loaded

        os.makedirs(self._dir(), exist_ok=True)
        images_per_client = []
        for c in range(self.num_classes):
            sel = ytr == c
            atomic_save(os.path.join(self._dir(), f"client{c}.npy"),
                        xtr[sel])
            images_per_client.append(int(sel.sum()))
        atomic_savez(os.path.join(self._dir(), "val.npz"),
                     images=xva, labels=yva)
        # the source + generator-version stamp is what
        # _cached_stats_ok uses to invalidate a cache that is stale
        # (v1 corpus) or of the wrong provenance (synthetic .npy left
        # behind after real pickles appeared)
        self.write_stats(
            images_per_client, len(yva),
            extra=({"source": "pickles"} if loaded is not None else
                   {"source": "synthetic",
                    "synthetic_version": _SYNTH_VERSION,
                    "synthetic_signal": self._synthetic_signal}))

    def _client_images(self, cid: int) -> np.ndarray:
        if cid not in self._cache:
            self._cache[cid] = np.load(
                os.path.join(self._dir(), f"client{cid}.npy"))
        return self._cache[cid]

    def _get_train_batch(self, nat_client_id: int, idxs: np.ndarray):
        imgs = self._client_images(nat_client_id)[idxs]
        # label == natural client id (reference fed_cifar.py:77-84)
        labels = np.full(len(idxs), nat_client_id, np.int64)
        return imgs, labels

    def _get_val_batch(self, idxs: np.ndarray):
        if "val" not in self._cache:
            z = np.load(os.path.join(self._dir(), "val.npz"))
            self._cache["val"] = (z["images"], z["labels"])
        imgs, labels = self._cache["val"]
        return imgs[idxs], labels[idxs]


class FedCIFAR100(FedCIFAR10):
    num_classes = 100

    def __init__(self, dataset_dir, dataset_name="CIFAR100", **kw):
        super().__init__(dataset_dir, dataset_name, **kw)

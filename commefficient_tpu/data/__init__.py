from commefficient_tpu.data.fed_dataset import FedDataset  # noqa: F401
from commefficient_tpu.data.sampler import (  # noqa: F401
    FedSampler, ValSampler, RoundIndices,
)
from commefficient_tpu.data.loader import FedLoader, FedValLoader  # noqa: F401
from commefficient_tpu.data.cifar import FedCIFAR10, FedCIFAR100  # noqa: F401
from commefficient_tpu.data.emnist import FedEMNIST  # noqa: F401
from commefficient_tpu.data.imagenet import FedImageNet  # noqa: F401
from commefficient_tpu.data.persona import (  # noqa: F401
    FedPERSONA, HashTokenizer, make_tokenizer,
)
from commefficient_tpu.data import transforms  # noqa: F401

"""Federated EMNIST (LEAF FEMNIST): one client per writer.

Capability parity with the reference's EMNIST layer (reference:
CommEfficient/data_utils/fed_emnist.py — LEAF per-user JSON parsing
`read_data` :11-33; concatenation into big arrays + per-client offsets
to dodge fd limits :40-58; 28x28x1 images, 62 classes). TPU-first
re-design: everything lands in one memory-mapped .npz (images,
targets, offsets) — the reference's fd-limit workaround becomes the
natural storage layout, and fetches are pure numpy slices.

Sources, in order of preference:
  1. LEAF JSON shards under <dataset_dir>/EMNIST/raw/{train,test}/*.json
     (the standard LEAF femnist output; keys `users`, `user_data`).
  2. `synthetic_examples=(num_writers, images_per_writer)` — a
     deterministic writer-heterogeneous synthetic corpus (per-class
     stroke template + per-writer style shift + noise) for
     environments without the dataset (no network egress).
"""
from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.utils.atomic_io import atomic_savez

NUM_CLASSES = 62
HW = 28


def read_leaf_dir(data_dir: str):
    """Parse every LEAF .json shard in `data_dir` into
    {user: (images [n, 28, 28, 1] uint8, labels [n] int64)}
    (reference read_data, fed_emnist.py:11-33; stdlib json instead of
    orjson, which is not in this environment)."""
    users = {}
    for fname in sorted(os.listdir(data_dir)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(data_dir, fname)) as f:
            shard = json.load(f)
        for user, ud in shard["user_data"].items():
            x = np.asarray(ud["x"], np.float32).reshape(-1, HW, HW, 1)
            # LEAF stores white-background floats in [0, 1]
            x = (x * 255).astype(np.uint8)
            y = np.asarray(ud["y"], np.int64)
            users[user] = (x, y)
    return users


# bump when _synthetic_emnist's semantics change: the on-disk cache is
# keyed by sizing + this stamp (see _cached_stats_ok)
_SYNTH_VERSION = 1


def _synthetic_emnist(num_writers: int, per_writer: int, n_val: int,
                      seed: int):
    """Writer-heterogeneous synthetic handwriting: class templates +
    per-writer style shift, mirroring FEMNIST's non-IIDness."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(NUM_CLASSES, HW, HW, 1).astype(np.float32)

    def writer(w_seed, n):
        wrng = np.random.RandomState(w_seed)
        style = wrng.randn(HW, HW, 1).astype(np.float32) * 0.1
        y = wrng.randint(0, NUM_CLASSES, n)
        x = templates[y] + style + wrng.randn(n, HW, HW, 1).astype(
            np.float32) * 0.05
        return (np.clip(x, 0, 1) * 255).astype(np.uint8), y

    train = [writer(seed * 77 + w, per_writer) for w in range(num_writers)]
    val_x, val_y = writer(seed * 77 - 1, n_val)
    return train, (val_x, val_y)


class FedEMNIST(FedDataset):
    num_classes = NUM_CLASSES

    def __init__(self, dataset_dir, dataset_name="EMNIST", transform=None,
                 do_iid=False, num_clients=None, train=True, download=False,
                 synthetic_examples: Optional[Tuple[int, int]] = None,
                 seed: int = 0):
        self._synthetic_examples = synthetic_examples
        self._seed = seed
        self._z = {}
        super().__init__(dataset_dir, dataset_name, transform, do_iid,
                         num_clients, train, download, seed)

    def _dir(self):
        return os.path.join(self.dataset_dir, self.dataset_name)

    def _npz_path(self, split: str) -> str:
        return os.path.join(self._dir(), f"{split}.npz")

    def _cached_stats_ok(self) -> bool:
        """Re-prepare when the cached corpus isn't the one that would
        be prepared NOW (same contract as FedCIFAR10._cached_stats_ok:
        real LEAF shards on disk always win, so a synthetic-stamped
        cache is stale once they appear; a synthetic cache must match
        the requested sizing and generator version)."""
        try:
            import json
            with open(self.stats_path()) as f:
                stats = json.load(f)
        except (OSError, ValueError):
            # missing/unreadable/torn stats file -> re-prepare; anything
            # else (incl. InjectedFault from the fault harness) raises
            return False
        if os.path.isdir(os.path.join(self._dir(), "raw", "train")):
            return stats.get("source") == "leaf"
        if self._synthetic_examples is None:
            return True
        writers, per_writer = self._synthetic_examples
        ipc = stats["images_per_client"]
        return (stats.get("source") == "synthetic"
                and stats.get("synthetic_version") == _SYNTH_VERSION
                and len(ipc) == writers
                and all(n == per_writer for n in ipc))

    def prepare(self, download: bool = False):
        raw_train = os.path.join(self._dir(), "raw", "train")
        raw_test = os.path.join(self._dir(), "raw", "test")
        if os.path.isdir(raw_train):
            users = read_leaf_dir(raw_train)
            train = [users[u] for u in sorted(users)]
            test_users = (read_leaf_dir(raw_test)
                          if os.path.isdir(raw_test) else {})
            if test_users:
                vx = np.concatenate([x for x, _ in test_users.values()])
                vy = np.concatenate([y for _, y in test_users.values()])
            else:
                vx = np.zeros((0, HW, HW, 1), np.uint8)
                vy = np.zeros((0,), np.int64)
        elif self._synthetic_examples is not None:
            writers, per_writer = self._synthetic_examples
            train, (vx, vy) = _synthetic_emnist(
                writers, per_writer, n_val=max(per_writer * 4, 64),
                seed=self._seed)
        else:
            raise FileNotFoundError(
                f"No LEAF shards under {raw_train} and no network egress; "
                f"pass synthetic_examples=(num_writers, images_per_writer)")

        os.makedirs(self._dir(), exist_ok=True)
        images = np.concatenate([x for x, _ in train])
        targets = np.concatenate([y for _, y in train])
        offsets = np.concatenate(
            [[0], np.cumsum([len(y) for _, y in train])])
        atomic_savez(self._npz_path("train"), images=images,
                     targets=targets, offsets=offsets)
        atomic_savez(self._npz_path("val"), images=vx, labels=vy)
        from_leaf = os.path.isdir(raw_train)
        self.write_stats(
            [len(y) for _, y in train], len(vy),
            extra=({"source": "leaf"} if from_leaf else
                   {"source": "synthetic",
                    "synthetic_version": _SYNTH_VERSION}))

    def _load(self, split: str):
        if split not in self._z:
            self._z[split] = dict(np.load(self._npz_path(split)))
        return self._z[split]

    def _get_train_batch(self, nat_client_id: int, idxs: np.ndarray):
        z = self._load("train")
        start = z["offsets"][nat_client_id]
        sel = start + np.asarray(idxs)
        return z["images"][sel], z["targets"][sel]

    def _get_val_batch(self, idxs: np.ndarray):
        z = self._load("val")
        return z["images"][idxs], z["labels"][idxs]

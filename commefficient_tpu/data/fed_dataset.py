"""Federated dataset base: client partitioning + metadata.

Capability parity with the reference data layer's core abstractions
(reference: CommEfficient/data_utils/fed_dataset.py — flat-index ->
(client_id, datum) mapping at :68-95, `data_per_client` at :31-48,
`stats.json` metadata at :55-59,97-98; non-IID natural partitions and
IID reshuffle at :28-29,71-75).

Host-side numpy only — the TPU program never sees ragged structures;
`commefficient_tpu.data.sampler` turns this into padded, static-shape
round batches.
"""
from __future__ import annotations

import json
import os
from typing import Optional, Sequence, Tuple

import numpy as np

from commefficient_tpu.utils.atomic_io import atomic_write_text


class FedDataset:
    """Base class: a train corpus partitioned over clients, plus a flat
    validation set.

    Subclasses implement `prepare()` (fill `self.images_per_client`,
    `self.num_val_images`, and storage) and the two fetchers
    `_get_train_batch(client_id, idxs)` / `_get_val_batch(idxs)`, each
    returning a tuple of stacked numpy arrays.
    """

    def __init__(self, dataset_dir: str, dataset_name: str,
                 transform=None, do_iid: bool = False,
                 num_clients: Optional[int] = None, train: bool = True,
                 download: bool = False, seed: int = 0):
        self.dataset_dir = dataset_dir
        self.dataset_name = dataset_name
        self.transform = transform
        self.do_iid = do_iid
        self._num_clients = num_clients
        self.train = train

        if not do_iid and num_clients == 1:
            raise ValueError("can't have 1 client when non-iid")

        if (not os.path.exists(self.stats_path())
                or not self._cached_stats_ok()):
            self.prepare(download=download)
        self._load_meta()

        if self.do_iid:
            # IID: a fixed permutation reassigns data to clients
            # uniformly (reference fed_dataset.py:28-29,71-75)
            rng = np.random.RandomState(seed)
            self.iid_shuffle = rng.permutation(len(self))

        # precompute flat-index offsets of the natural partition
        self._nat_cumsum = np.concatenate(
            [[0], np.cumsum(self.images_per_client)])

    # ---- metadata -------------------------------------------------------
    def stats_path(self) -> str:
        return os.path.join(self.dataset_dir, self.dataset_name,
                            "stats.json")

    def write_stats(self, images_per_client: Sequence[int],
                    num_val_images: int, extra: Optional[dict] = None):
        """`extra`: dataset-specific metadata written alongside the
        counts in one shot — e.g. the synthetic-generator version and
        corpus source that _cached_stats_ok implementations use to
        invalidate stale caches (a semantic change to a generator
        must not silently serve the pre-change corpus)."""
        os.makedirs(os.path.dirname(self.stats_path()), exist_ok=True)
        stats = {"images_per_client": [int(x) for x in images_per_client],
                 "num_val_images": int(num_val_images)}
        if extra:
            stats.update(extra)
        # atomic (GL006): a preemption mid-write must not leave a torn
        # stats file shadowing an intact cache — _cached_stats_ok would
        # read garbage and re-prepare over good data
        atomic_write_text(self.stats_path(), json.dumps(stats))

    def _load_meta(self):
        with open(self.stats_path()) as f:
            stats = json.load(f)
        self.images_per_client = np.array(stats["images_per_client"])
        self.num_val_images = int(stats["num_val_images"])

    def _cached_stats_ok(self) -> bool:
        """Is the on-disk prepared dataset the one THIS construction
        asks for? Subclasses with a sized synthetic fallback override
        this to compare the cached stats against the requested sizing —
        without the check, constructing with different
        `synthetic_examples` silently reuses whatever sizing was
        prepared first in the same dataset_dir (a 2000-example cache
        once served a run that asked for 400)."""
        return True

    # ---- partition geometry --------------------------------------------
    @property
    def num_clients(self) -> int:
        return (self._num_clients if self._num_clients is not None
                else len(self.images_per_client))

    @property
    def data_per_client(self) -> np.ndarray:
        """Per-client example counts after resharding the natural
        partition over `num_clients` (reference fed_dataset.py:31-48:
        each natural unit — a class, writer, persona — is split across
        num_clients/num_units clients)."""
        if self.do_iid:
            n = len(self)
            per = np.full(self.num_clients, n // self.num_clients, dtype=int)
            per[self.num_clients - (n % self.num_clients):] += 1 \
                if n % self.num_clients else 0
            return per
        out = []
        n_units = len(self.images_per_client)
        per_unit = (self._num_clients // n_units
                    if self._num_clients is not None else 1)
        if per_unit < 1 or (self._num_clients is not None
                            and self._num_clients % n_units):
            # the reference dies with a bare ZeroDivisionError below the
            # unit count and silently builds a partition shorter than
            # num_clients for non-multiples (fed_dataset.py:42-44, then
            # an IndexError downstream); fail with an actionable message
            raise ValueError(
                f"non-IID partition needs num_clients to be a positive "
                f"multiple of the natural unit count ({n_units}; one "
                f"class/writer/persona per unit), got "
                f"num_clients={self._num_clients}. Use a multiple of "
                f"{n_units}, or --iid.")
        for n_images in self.images_per_client:
            counts = [n_images // per_unit] * per_unit
            counts[-1] += n_images % per_unit
            out.extend(counts)
        return np.array(out)

    def __len__(self) -> int:
        if self.train:
            return int(np.sum(self.images_per_client))
        return self.num_val_images

    # ---- fetch ----------------------------------------------------------
    def client_flat_indices(self, client_id: int,
                            idx_within: np.ndarray) -> np.ndarray:
        """Map (client, local index) to flat dataset indices."""
        dpc_cumsum = np.concatenate([[0], np.cumsum(self.data_per_client)])
        flat = dpc_cumsum[client_id] + idx_within
        if self.do_iid:
            flat = self.iid_shuffle[flat]
        return flat

    def get_client_batch(self, client_id: int,
                         idx_within: np.ndarray) -> Tuple[np.ndarray, ...]:
        """Fetch one client's (transformed) examples by local index."""
        flat = self.client_flat_indices(client_id, np.asarray(idx_within))
        # flat index -> (natural client, index within natural client)
        nat = np.searchsorted(self._nat_cumsum, flat, side="right") - 1
        within = flat - self._nat_cumsum[nat]
        batch = self._gather_train(nat, within)
        if self.transform is not None:
            batch = self.transform(*batch)
        return batch

    def get_val_batch(self, idxs: np.ndarray) -> Tuple[np.ndarray, ...]:
        batch = self._get_val_batch(np.asarray(idxs))
        if self.transform is not None:
            batch = self.transform(*batch)
        return batch

    def _gather_train(self, nat_clients: np.ndarray,
                      idx_within: np.ndarray) -> Tuple[np.ndarray, ...]:
        """Default gather: group by natural client and concatenate."""
        parts = []
        order = np.argsort(nat_clients, kind="stable")
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        sorted_nat = nat_clients[order]
        sorted_within = idx_within[order]
        outs = None
        for cid in np.unique(sorted_nat):
            sel = sorted_nat == cid
            got = self._get_train_batch(int(cid), sorted_within[sel])
            if outs is None:
                outs = [[] for _ in got]
            for o, g in zip(outs, got):
                o.append(g)
        stacked = [np.concatenate(o, axis=0) for o in outs]
        return tuple(s[inv] for s in stacked)

    # ---- subclass API ---------------------------------------------------
    def prepare(self, download: bool = False):
        raise NotImplementedError

    def _get_train_batch(self, nat_client_id: int, idxs: np.ndarray):
        raise NotImplementedError

    def _get_val_batch(self, idxs: np.ndarray):
        raise NotImplementedError

"""Round-batch assembly: dataset + sampler -> device-ready arrays.

The glue the reference spreads across torch DataLoader construction
(reference: CommEfficient/cv_train.py:254-287) and the per-round
client grouping inside the aggregator (fed_aggregator.py:218-237).
Here grouping is free — the sampler already emits [num_workers, B]
per-client blocks — and batches go to the device as single contiguous
NHWC arrays.
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.data.sampler import FedSampler, RoundIndices, ValSampler


class FedLoader:
    """Iterates training rounds: for each RoundIndices, fetches and
    transforms every participating client's examples and stacks them
    into (client_ids [W], data pytree [W, B, ...], mask [W, B])."""

    def __init__(self, dataset: FedDataset, num_workers: int,
                 local_batch_size: int, seed: int = 0,
                 max_local_batch: int = -1):
        self.dataset = dataset
        self.sampler = FedSampler(dataset.data_per_client, num_workers,
                                  local_batch_size, seed=seed,
                                  max_local_batch=max_local_batch)

    @property
    def steps_per_epoch(self) -> int:
        return self.sampler.steps_per_epoch()

    def epoch(self) -> Iterator[Tuple[np.ndarray, Tuple[np.ndarray, ...],
                                      np.ndarray]]:
        B = self.sampler.round_batch_size
        for r in self.sampler.epoch():
            per_client = []
            for w, cid in enumerate(r.client_ids):
                n_valid = int(r.mask[w].sum())
                got = self.dataset.get_client_batch(
                    int(cid), r.idx_within[w, :n_valid])
                per_client.append((n_valid, got))
            # allocate static [W, B, ...] buffers from the first fetch
            protos = per_client[0][1]
            data = tuple(
                np.zeros((len(r.client_ids), B) + p.shape[1:], p.dtype)
                for p in protos)
            for w, (n_valid, got) in enumerate(per_client):
                for buf, g in zip(data, got):
                    buf[w, :n_valid] = g
            yield r.client_ids, data, r.mask


class FedValLoader:
    """Validation batches as [num_shards, valid_batch_size, ...] blocks
    (reference _call_val sharding, fed_aggregator.py:337-348)."""

    def __init__(self, dataset: FedDataset, valid_batch_size: int,
                 num_shards: int):
        self.dataset = dataset
        self.sampler = ValSampler(dataset.num_val_images, valid_batch_size,
                                  num_shards)
        self.vb = valid_batch_size
        self.num_shards = num_shards

    def batches(self):
        for r in self.sampler.batches():
            flat_idx = r.idx_within.reshape(-1)
            got = self.dataset.get_val_batch(flat_idx)
            data = tuple(
                g.reshape((self.num_shards, self.vb) + g.shape[1:])
                for g in got)
            yield data, r.mask

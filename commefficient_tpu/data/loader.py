"""Round-batch assembly: dataset + sampler -> device-ready arrays.

The glue the reference spreads across torch DataLoader construction
(reference: CommEfficient/cv_train.py:254-287) and the per-round
client grouping inside the aggregator (fed_aggregator.py:218-237).
Here grouping is free — the sampler already emits [num_workers, B]
per-client blocks — and batches go to the device as single contiguous
NHWC arrays.
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.data.sampler import FedSampler, RoundIndices, ValSampler


class FedLoader:
    """Iterates training rounds: for each RoundIndices, fetches and
    transforms every participating client's examples and stacks them
    into (client_ids [W], data pytree [W, B, ...], mask [W, B])."""

    def __init__(self, dataset: FedDataset, num_workers: int,
                 local_batch_size: int, seed: int = 0,
                 max_local_batch: int = -1,
                 feed_slice: Optional[slice] = None):
        """feed_slice: per-process batch feeding for multi-controller
        runs (parallel/multihost.local_row_slice) — the sampler still
        runs over the GLOBAL round (identical on every process, it is
        pure seeded index math), but only the rows in `feed_slice` are
        fetched/transformed/materialized. Yielded batches then carry
        global client_ids with process-local data/mask rows, which is
        exactly FedModel._call_train's multi-controller contract."""
        self.dataset = dataset
        self.sampler = FedSampler(dataset.data_per_client, num_workers,
                                  local_batch_size, seed=seed,
                                  max_local_batch=max_local_batch)
        self.feed_slice = feed_slice

    @property
    def steps_per_epoch(self) -> int:
        return self.sampler.steps_per_epoch()

    def epoch(self, skip: int = 0
              ) -> Iterator[Tuple[np.ndarray, Tuple[np.ndarray, ...],
                                  np.ndarray]]:
        """skip: advance past the first `skip` rounds using sampler
        index math only — no fetch/transform/materialization — for
        O(1)-per-round mid-epoch resume fast-forward (the sampler's RNG
        state still advances identically to a full epoch)."""
        B = self.sampler.round_batch_size
        for r in self.sampler.epoch():
            if skip > 0:
                skip -= 1
                continue
            W = len(r.client_ids)
            rows = (range(W) if self.feed_slice is None
                    else range(*self.feed_slice.indices(W)))
            if len(rows) == 0:
                raise NotImplementedError(
                    "this process owns no rows of the clients axis; "
                    "zero-row feeding is not supported — use a mesh "
                    "layout that gives every process client shards")
            per_client = []
            for w in rows:
                n_valid = int(r.mask[w].sum())
                # idle slots (a scheduler that over-provisioned fewer
                # than num_workers pads with zero-mask rows) fetch
                # nothing: their buffer rows stay zeros and the round
                # engine sees them as survivor-0 dead slots
                got = (self.dataset.get_client_batch(
                    int(r.client_ids[w]), r.idx_within[w, :n_valid])
                    if n_valid else None)
                per_client.append((n_valid, got))
            # allocate static [W_local, B, ...] buffers from the first
            # real fetch (slot 0 is always active in single-controller
            # runs — the scheduler selects at least one participant)
            protos = next((got for _, got in per_client
                           if got is not None), None)
            if protos is None:
                raise NotImplementedError(
                    "every row this process feeds is an idle "
                    "(zero-mask) slot; feeding cannot derive batch "
                    "shapes — scheduler over-provisioning is single-"
                    "controller only (Config.validate enforces this)")
            data = tuple(
                np.zeros((len(rows), B) + p.shape[1:], p.dtype)
                for p in protos)
            for i, (n_valid, got) in enumerate(per_client):
                if got is None:
                    continue
                for buf, g in zip(data, got):
                    buf[i, :n_valid] = g
            mask = (r.mask if self.feed_slice is None
                    else r.mask[self.feed_slice])
            yield r.client_ids, data, mask


class FedValLoader:
    """Validation batches as [num_shards, valid_batch_size, ...] blocks
    (reference _call_val sharding, fed_aggregator.py:337-348)."""

    def __init__(self, dataset: FedDataset, valid_batch_size: int,
                 num_shards: int, feed_slice: Optional[slice] = None):
        """feed_slice: as FedLoader — only the shard rows this process
        feeds are fetched in multi-controller runs."""
        self.dataset = dataset
        self.sampler = ValSampler(dataset.num_val_images, valid_batch_size,
                                  num_shards)
        self.vb = valid_batch_size
        self.num_shards = num_shards
        self.feed_slice = feed_slice

    def batches(self):
        for r in self.sampler.batches():
            idx = r.idx_within
            mask = r.mask
            if self.feed_slice is not None:
                idx = idx[self.feed_slice]
                mask = mask[self.feed_slice]
            flat_idx = idx.reshape(-1)
            got = self.dataset.get_val_batch(flat_idx)
            data = tuple(
                g.reshape((idx.shape[0], self.vb) + g.shape[1:])
                for g in got)
            yield data, mask
